"""Typed command-argument parsing for the stack.

Parity with the reference parser utilities: ``Argparser`` argtype dispatch
(stack/stack.py:1467-1748) and the text converters in ``tools/misc.py``
(txt2alt :18, txt2spd :66, txt2lat/lon, cmdsplit :125) — reimplemented as
small pure functions keyed by argtype name.  Position text resolution
(``tools/position.py``) consults the navdatabase when one is attached.

Supported argtypes (subset used by the built-in command dict, same names as
the reference): txt (uppercased), word (case-preserving — use for
filenames), string, acid, wpinroute, float, int, onoff, alt, spd,
vspd, hdg, time, latlon, lat, lon, wpt, pandir, color.  A trailing
``...`` repeats the last group.  Optional args are marked with brackets in
the usage string and simply absent from the tail.
"""
import re
from typing import Any, List, Optional, Tuple

from ..ops import aero


class NamedPos(tuple):
    """(lat, lon) that remembers the resolved position's name."""
    name = None


class ArgError(Exception):
    pass


def cmdsplit(cmdline: str) -> List[str]:
    """Split a command line on commas/spaces, preserving empty slots from
    adjacent commas (tools/misc.py:125-150)."""
    cmdline = cmdline.strip()
    if not cmdline:
        return []
    if ',' in cmdline:
        parts = [p.strip() for p in re.split(',', cmdline)]
        # allow spaces inside first arg block
        out = []
        for p in parts:
            if out:
                out.append(p)
            else:
                out.extend(p.split())
        return out
    return cmdline.split()


# Unit converters live in utils/units.py (shared with the core
# route layer); re-exported here for the argtype table and
# existing importers.
from ..utils.units import (txt2alt, txt2spd, txt2vspd,  # noqa: E402,F401
                           txt2hdg, txt2time, txt2lat, txt2lon)


_ISLATLON = re.compile(r"^[NSEW]?[-+]?[\d.]+[NSEW]?$")


class Argparser:
    """Parse an argument list against a comma-separated argtype spec."""

    def __init__(self, sim):
        self.sim = sim   # for acid lookup, navdb, reflat/lon

    def parse(self, argtypes: str, args: List[str]) -> List[Any]:
        """Returns converted argument values; raises ArgError on mismatch.

        Mirrors Argparser.parse (stack.py:1467-1560): optional args are
        bracketed in the spec ('[alt]'), a trailing '...' repeats the
        preceding group for any remaining arguments.  'latlon' consumes two
        numeric tokens (lat, lon) or one named-position token and yields a
        (lat, lon) tuple.
        """
        # Preprocess the spec: tokens split on commas; '[' opens an optional
        # region spanning tokens until the matching ']' (reference usage
        # strings group several optionals in one bracket, e.g.
        # "acid,latlon,[alt,spd,afterwp]"); '...' marks the rest repeating.
        tokens: List[Tuple[str, bool]] = []   # (argtype, optional)
        repeating = False
        depth = 0
        for raw in (argtypes.split(",") if argtypes else []):
            t = raw.strip()
            opens = t.count("[")
            closes = t.count("]")
            t = t.strip("[]").strip()
            was_optional = depth > 0 or opens > 0
            depth += opens - closes
            if t == "...":
                repeating = True
                continue
            if t:
                tokens.append((t, was_optional))

        out: List[Any] = []
        self._last_acid = -1       # reference position for named waypoints
        ai = 0
        si = 0
        while si < len(tokens) or (repeating and ai < len(args)):
            if si < len(tokens):
                st2, optional = tokens[si]
            else:
                st2, optional = tokens[-1] if tokens else ("string", True)
            if ai >= len(args) or args[ai] == "":
                if ai < len(args):    # empty placeholder token, e.g. "A,,B"
                    out.append(None)
                    ai += 1
                    si += 1
                    continue
                if optional or si >= len(tokens):
                    break
                raise ArgError(f"missing argument <{st2}>")
            if st2 == "string" and not repeating and si == len(tokens) - 1:
                # Greedy rest-of-line (reference stack.py 'string' argtype)
                # — only as the FINAL spec token; 'string,...' specs
                # (DELAY/SYN/PCALL) keep per-token parsing, their handlers
                # re-join or index the words.
                out.append(" ".join(a for a in args[ai:] if a != ""))
                ai = len(args)
            elif st2 == "latlon":
                val, consumed = self._parse_latlon(args, ai)
                out.append(val)
                ai += consumed
            elif st2 == "wppos":
                # Waypoint position for route editing: the FLYBY/FLYOVER
                # turn-mode keywords win over any same-named navdb fix
                # (reference route.py:77-92 checks the keyword BEFORE
                # resolving — there IS a US fix named FLYBY)
                kw = args[ai].strip().upper()
                if kw in ("FLYBY", "FLY-BY", "FLYOVER", "FLY-OVER"):
                    np_ = NamedPos((0.0, 0.0))
                    np_.name = kw
                    out.append(np_)
                    ai += 1
                else:
                    val, consumed = self._parse_latlon(args, ai)
                    out.append(val)
                    ai += consumed
            else:
                out.append(self.parse_arg(st2, args[ai], out))
                ai += 1
            si += 1
        if ai < len(args) and not repeating:
            raise ArgError(f"too many arguments: {' '.join(args[ai:])}")
        return out

    def _parse_latlon(self, args: List[str], ai: int):
        """(lat, lon) from two numeric tokens or one named position.

        Named positions come back as a NamedPos (a (lat, lon) tuple that
        also carries .name) so route commands can keep the waypoint name
        (reference wpt argtype keeps names, stack.py Argparser)."""
        t = args[ai].strip()
        if _ISLATLON.match(t.upper()) and any(c.isdigit() for c in t):
            if ai + 1 >= len(args):
                raise ArgError("latlon: missing longitude")
            return (txt2lat(t), txt2lon(args[ai + 1])), 2
        # Named position: navdb lookup if attached.  When an aircraft was
        # parsed earlier in this command its position disambiguates
        # duplicate waypoint names (reference position.py/getwpidx
        # semantics).
        navdb = getattr(self.sim, "navdb", None)
        if navdb is not None:
            reflat = reflon = 999999.0
            idx = self._last_acid
            if idx >= 0:
                ac = self.sim.traf.state.ac
                reflat = float(ac.lat[idx])    # single-element transfer
                reflon = float(ac.lon[idx])
            pos = navdb.txt2pos(t, reflat, reflon)
            if pos is not None:
                np_ = NamedPos((pos[0], pos[1]))
                np_.name = t.upper()
                return np_, 1
        raise ArgError(f"{t}: position not found")

    def parse_arg(self, argtype: str, txt: str, sofar: List[Any]):
        t = txt.strip()
        # Union types 'a/b' (reference e.g. 'acid/txt', 'float/txt'):
        # first alternative that parses wins.
        if "/" in argtype:
            err = None
            for alt in argtype.split("/"):
                try:
                    return self.parse_arg(alt.strip(), txt, sofar)
                except ArgError as e:
                    err = e
            raise err
        try:
            if argtype in ("txt", "string", "word"):
                return t.upper() if argtype == "txt" else t
            if argtype == "acid":
                idx = self.sim.traf.id2idx(t)
                if idx < 0:
                    raise ArgError(f"{t}: aircraft not found")
                self._last_acid = idx
                return idx
            if argtype == "wpinroute":
                return t.upper()
            if argtype == "float":
                return float(t)
            if argtype == "int":
                return int(float(t))
            if argtype == "onoff":
                u = t.upper()
                if u in ("ON", "TRUE", "YES", "1"):
                    return True
                if u in ("OFF", "FALSE", "NO", "0"):
                    return False
                raise ArgError(f"{t}: expected ON/OFF")
            if argtype == "alt":
                return txt2alt(t)
            if argtype == "spd":
                return txt2spd(t)
            if argtype == "vspd":
                return txt2vspd(t)
            if argtype == "hdg":
                return txt2hdg(t)
            if argtype == "time":
                return txt2time(t)
            if argtype == "lat":
                return txt2lat(t)
            if argtype == "lon":
                return txt2lon(t)
            if argtype == "latlon":
                # Either two numeric tokens (lat lon — caller passes lat here
                # and we signal to consume the next token), or a named
                # position resolved via the navdb.
                raise ArgError("latlon handled by parse()")
            if argtype == "wpt":
                return t.upper()
            if argtype == "pandir":
                u = t.upper()
                if u in ("LEFT", "RIGHT", "UP", "DOWN"):
                    return u
                raise ArgError(f"{t}: expected LEFT/RIGHT/UP/DOWN")
            if argtype == "color":
                return t.upper()
        except ArgError:
            raise
        except Exception as e:
            raise ArgError(f"{t}: invalid {argtype} ({e})")
        raise ArgError(f"unknown argtype {argtype}")
