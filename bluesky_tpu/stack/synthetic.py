"""SYN — parametric synthetic conflict geometries.

Parity with reference ``bluesky/stack/synthetic.py:13-438``: the SIMPLE /
SIMPLED / SUPER / SPHERE / MATRIX / FLOOR / TAKEOVER / WALL / ROW / COLUMN
generators used by the ASAS benchmark scenarios (geometry constants — 0.5 deg
circle radius, 200 kts, FL200, 1.1 formation spacing factor — kept so the
ASAS-* benchmark workloads are comparable).  Aircraft go through the normal
batched ``Traffic.create`` path, so a ``SYN SUPER 10000`` lands on device in
one flush.
"""
import numpy as np

from ..ops import aero

MPERDEG = 111319.0


def process(sim, subcmd, args):
    traf = sim.traf
    if subcmd is None or subcmd.upper() == "HELP":
        return True, ("SYN: synthetic traffic scenarios\n"
                      "Subcommands: SIMPLE, SIMPLED, SUPER n, SPHERE n, "
                      "MATRIX n, FLOOR, TAKEOVER n, WALL, ROW n ang, "
                      "COLUMN n ang")
    c = subcmd.upper()
    nargs = len(args)

    def reset():
        # Traffic-only, like the reference generators' bs.traf.reset()
        # (synthetic.py:48-327): sim settings/stack/logs must survive.
        sim.reset_traffic()

    if c == "SIMPLE":
        reset()
        traf.create(1, "B744", 5000 * aero.ft, 200.0, None, -0.5, 0.0, 0.0,
                    "OWNSHIP")
        traf.create(1, "B744", 5000 * aero.ft, 200.0, None, 0.0, 0.5, 270.0,
                    "INTRUDER")
        traf.flush()
        return True

    if c == "SIMPLED":
        reset()
        rng = traf._rng
        ds = rng.uniform(0.92, 1.08)
        dd = rng.uniform(0.92, 1.08)
        traf.create(1, "B744", 20000 * aero.ft, 200.0 * ds, None, -0.5 * dd,
                    0.0, 0.0, "OWNSHIP")
        traf.create(1, "B744", 20000 * aero.ft, 200.0 / ds, None, 0.0,
                    0.5 / dd, 270.0, "INTRUDER")
        traf.flush()
        return True

    if c == "SUPER":
        if nargs == 0:
            return True, "SYN SUPER <number of aircraft>"
        reset()
        numac = int(float(args[0]))
        dist = 0.5
        ang = 2 * np.pi / numac * np.arange(numac)
        traf.create(numac, "B744",
                    np.full(numac, 20000 * aero.ft),
                    np.full(numac, 200.0 * aero.kts), None,
                    dist * -np.cos(ang), dist * np.sin(ang),
                    360.0 - 360.0 / numac * np.arange(numac))
        traf.flush()
        return True

    if c == "SPHERE":
        if nargs == 0:
            return True, "SYN SPHERE <aircraft per layer>"
        reset()
        numac = int(float(args[0]))
        dist = 0.5
        # Three layers converging towards the same volume: middle level,
        # upper descending, lower climbing (reference synthetic.py:110-164).
        for layer, (dalt, vs_sign) in enumerate(
                [(0.0, 0), (3000.0 * aero.ft, -1), (-3000.0 * aero.ft, 1)]):
            ang = 2 * np.pi / numac * (np.arange(numac) + 0.5 * layer)
            ids = [f"SPH{layer}_{i}" for i in range(numac)]
            traf.create(numac, "B744",
                        np.full(numac, 20000 * aero.ft + dalt),
                        np.full(numac, 150.0 * aero.kts), None,
                        dist * -np.cos(ang), dist * np.sin(ang),
                        np.degrees(ang) % 360.0, acid=None)
        traf.flush()
        return True

    if c == "MATRIX":
        if nargs == 0:
            return True, "SYN MATRIX <size>"
        reset()
        size = int(float(args[0]))
        hseplat = sim.cfg.asas.rpz / MPERDEG * 1.1
        vel = 200.0
        extradist = (vel * 1.1) * 5 * 60 / MPERDEG
        k = np.arange(size)
        off = (k - (size - 1.0) / 2) * hseplat
        edge = hseplat * (size - 1.0) / 2 + extradist
        alt = np.full(size, 20000 * aero.ft)
        spd = np.full(size, vel)   # m/s > 1 => CAS in m/s
        traf.create(size, "B744", alt, spd, None, np.full(size, edge), off,
                    np.full(size, 180.0))
        traf.create(size, "B744", alt, spd, None, np.full(size, -edge), off,
                    np.full(size, 0.0))
        traf.create(size, "B744", alt, spd, None, off, np.full(size, edge),
                    np.full(size, 270.0))
        traf.create(size, "B744", alt, spd, None, off, np.full(size, -edge),
                    np.full(size, 90.0))
        traf.flush()
        return True

    if c == "FLOOR":
        reset()
        hseplat = sim.cfg.asas.rpz / MPERDEG * 1.1
        traf.create(1, "B744", 23000 * aero.ft, 200.0, None, -1.0, 0.0, 90.0,
                    "OWNSHIP")
        traf.flush()
        idx = traf.id2idx("OWNSHIP")
        s = traf.state
        traf.state = s.replace(ac=s.ac.replace(
            selvs=s.ac.selvs.at[idx].set(-10.0),
            selalt=s.ac.selalt.at[idx].set(17000 * aero.ft)))
        n = 20
        traf.create(n, "B744", np.full(n, 20000 * aero.ft),
                    np.full(n, 200.0 * aero.kts), None,
                    np.full(n, -1.0), (np.arange(n) - 10) * hseplat,
                    np.full(n, 90.0))
        traf.flush()
        return True

    if c == "TAKEOVER":
        if nargs == 0:
            return True, "SYN TAKEOVER <number of aircraft>"
        reset()
        numac = int(float(args[0]))
        v = np.arange(50, 50 * (numac + 1), 50).astype(float)
        degtofly = v * 5 * 60 / MPERDEG
        traf.create(numac, "B744", np.full(numac, 20000 * aero.ft), v, None,
                    np.zeros(numac), -degtofly, np.full(numac, 90.0))
        traf.flush()
        return True

    if c == "WALL":
        reset()
        dist = 0.6
        hseplat = sim.cfg.asas.rpz / MPERDEG * 1.1
        traf.create(1, "B744", 20000 * aero.ft, 200.0, None, 0.0, -dist, 90.0,
                    "OWNSHIP")
        n = 20
        traf.create(n, "B744", np.full(n, 20000 * aero.ft),
                    np.full(n, 200.0 * aero.kts), None,
                    (np.arange(n) - 10) * hseplat, np.full(n, dist),
                    np.full(n, 270.0))
        traf.flush()
        return True

    if c in ("ROW", "COLUMN"):
        if nargs < 2:
            return True, f"SYN {c} n angle [radiusnm alt_ft spd_kts type]"
        reset()
        n = int(float(args[0]))
        ang = float(args[1])
        startdist = float(args[2]) * aero.nm / MPERDEG if nargs > 2 else 0.5
        acalt = float(args[3]) * aero.ft if nargs > 3 else 20000 * aero.ft
        acspd = float(args[4]) * aero.kts if nargs > 4 else 200 * aero.kts
        actype = args[5] if nargs > 5 else "B744"
        hseplat = sim.cfg.asas.rpz / MPERDEG * 1.1
        aclat = startdist * np.cos(np.radians(ang))
        aclon = startdist * np.sin(np.radians(ang))
        if c == "ROW":
            latsep = abs(hseplat * np.cos(np.radians(90 - ang)))
            lonsep = abs(hseplat * np.sin(np.radians(90 - ang)))
            alternate = 1
            for i in range(n):
                aclat = aclat + i * latsep * alternate
                aclon_i = aclon - i * lonsep * alternate
                traf.create(1, actype, acalt, acspd, None, aclat, aclon_i,
                            (180 + ang) % 360, f"ANG{2 * i}")
                traf.create(1, actype, acalt, acspd, None, aclat, -aclon_i,
                            (180 - ang) % 360, f"ANG{2 * i + 1}")
                alternate = -alternate
        else:
            latsep = abs(hseplat * np.cos(np.radians(ang)))
            lonsep = abs(hseplat * np.sin(np.radians(ang)))
            for i in range(n):
                la = aclat + i * latsep
                lo = aclon + i * lonsep
                traf.create(1, actype, acalt, acspd, None, la, lo,
                            (180 + ang) % 360, f"ANG{2 * i}")
                traf.create(1, actype, acalt, acspd, None, la, -lo,
                            (180 - ang) % 360, f"ANG{2 * i + 1}")
        traf.flush()
        return True

    return False, f"SYN: unknown subcommand {subcmd}"
