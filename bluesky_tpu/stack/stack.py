"""The command stack: parse, dispatch, scenario record/replay.

Parity with reference ``bluesky/stack/stack.py``: a pending-command list
drained each loop (process, stack.py:1359-1464), a command dictionary of
``name -> (usage, argtypes, function, help)`` (stack.py:180-796) with
synonyms (stack.py:44-115), timed scenario files ``HH:MM:SS.hh>CMD`` with
PCALL %0..%n argument substitution and REL/ABS offsets (openfile,
stack.py:1025-1115), due-command stacking per step (checkfile,
stack.py:1177-1183), DELAY/SCHEDULE insertion (sched_cmd, stack.py:1005-
1022), and SAVEIC command recording + state snapshot (stack.py:1185-1350).

The "acid first" fallback syntax (``KL204 LNAV ON``) and zoom shorthand are
kept.  Command registration is open: plugins and loggers append at runtime
via ``append_commands`` exactly like the reference (stack.py:837).
"""
import os
import re
from typing import Callable, Dict, List, Optional, Tuple

from .argparser import Argparser, ArgError, cmdsplit


class Stack:
    def __init__(self, sim):
        self.sim = sim
        self.parser = Argparser(sim)
        self.cmdstack: List[Tuple[str, str]] = []    # (cmdline, sender)
        self.cmddict: Dict[str, list] = {}           # NAME -> [usage, types, fn, help]
        self.synonyms: Dict[str, str] = {}
        # Scenario replay state
        self.scentime: List[float] = []
        self.scencmd: List[str] = []
        self.scenname = ""
        self.scenfile = ""        # last IC path (bare-IC reload)
        # SAVEIC recording
        self.savefile = None
        self.saveict0 = 0.0
        from .. import settings
        self.scenario_path = settings.scenario_path
        from . import commands
        commands.register_all(self)

    # --------------------------------------------------------- registration
    def append_commands(self, newcommands: Dict[str, list]):
        """Add/override commands at runtime (plugins, loggers)."""
        self.cmddict.update({k.upper(): v for k, v in newcommands.items()})

    def append_synonyms(self, syns: Dict[str, str]):
        self.synonyms.update({k.upper(): v.upper() for k, v in syns.items()})

    def remove_commands(self, names):
        """Remove commands (plugin unload, reference stack remove_commands)."""
        for n in names:
            self.cmddict.pop(n.upper(), None)

    # ------------------------------------------------------------- stacking
    def stack(self, cmdline: str, sender: str = ""):
        """Append commandline(s) to the pending stack (stack.py:997-1003)."""
        for line in cmdline.split(";"):
            if line.strip():
                self.cmdstack.append((line.strip(), sender))

    def process(self):
        """Drain and execute all pending commands (stack.py:1359-1464).

        Reentrancy-safe: the pending list is detached BEFORE execution,
        so a command that stacks and processes further commands (plugins
        like STACKCHECK do) cannot re-execute the lines already being
        drained."""
        while self.cmdstack:
            pending, self.cmdstack = self.cmdstack, []
            for cmdline, sender in pending:
                self._exec_cmdline(cmdline, sender)

    def _exec_cmdline(self, cmdline: str, sender: str = ""):
        # let the screen proxy route echo output back to the issuer
        self.sim.scr.current_sender = sender
        echo = self.sim.scr.echo
        args = cmdsplit(cmdline)
        if not args:
            return
        cmd = args[0].upper()
        rest = args[1:]

        # "acid first" syntax: KL204 LNAV ON -> LNAV KL204 ON; a bare
        # acid line means POS acid (stack.py:1390-1396)
        if cmd not in self.cmddict and cmd not in self.synonyms \
                and self.sim.traf.id2idx(cmd) >= 0:
            if rest:
                cmd, rest = rest[0].upper(), [args[0]] + rest[1:]
            else:
                cmd, rest = "POS", [args[0]]

        cmd = self.synonyms.get(cmd, cmd)
        entry = self.cmddict.get(cmd)
        if entry is None:
            # zoom shorthand: '+++'/'--' zoom by sqrt(2)^(n+ - n-),
            # '=' counts as '+' (same key) — reference stack.py:1436-1443
            if cmd[0] in "+=-" and set(cmd) <= set("+=-"):
                nplus = cmd.count("+") + cmd.count("=")
                self.sim.scr.zoom(2.0 ** (0.5 * (nplus - cmd.count("-"))))
                # never SAVEIC-recorded: ZOOM is in SAVEIC_EXCLUDE
                return
            echo(f"Unknown command: {cmd}")
            return

        usage, argtypes, fn = entry[0], entry[1], entry[2]
        try:
            parsed = self.parser.parse(argtypes, rest)
        except ArgError as e:
            echo(f"{cmd}: {e}")
            echo(f"Usage: {usage}")
            return

        # Any command may mutate traffic/display state: the ACDATA
        # stream must stop serving the cached chunk-edge telemetry
        # (simulation/pipeline.py) until the next edge retires.
        self.sim._last_edge = None
        try:
            result = fn(*parsed)
        except TypeError as e:
            # wrong arity for optional-arg functions
            echo(f"{cmd}: {e}")
            echo(f"Usage: {usage}")
            return
        except Exception as e:  # noqa: BLE001 — a command bug/bad input
            # must never kill the sim node (stack lines arrive from
            # remote clients); echo the failure instead.
            echo(f"{cmd} failed: {type(e).__name__}: {e}")
            return
        # Result protocol like the reference: True/False/None or
        # (success, echotext)
        if isinstance(result, tuple):
            ok, msg = result[0], result[1] if len(result) > 1 else ""
            if msg:
                echo(msg)
            if not ok and usage:
                echo(f"Usage: {usage}")
        elif result is False:
            echo(f"Usage: {usage}")
        # SAVEIC recording of successful commands (stack.py:1400-1401)
        if self.savefile is not None and result is not False \
                and cmd not in SAVEIC_EXCLUDE:
            self.savecmd(cmdline)

    # ------------------------------------------------------- scenario files
    def openfile(self, fname: str, pcall_args: Optional[List[str]] = None,
                 mergeWithExisting: bool = False, t_offset: float = 0.0):
        """Load a .scn file into (scentime, scencmd) (stack.py:1025-1115).

        Lines: ``[HH:MM:]SS[.hh]>CMD ...``; blank lines/comments (#) skipped;
        ``%0..%n`` substituted from pcall_args.
        """
        path = self._find_scn(fname)
        if path is None:
            return False, f"Scenario file {fname} not found"
        scentime, scencmd = [], []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                if ">" not in line:
                    continue
                tstr, cmd = line.split(">", 1)
                cmd = cmd.strip()
                if pcall_args:
                    for i, a in enumerate(pcall_args):
                        cmd = cmd.replace(f"%{i}", a)
                try:
                    from .argparser import txt2time
                    t = txt2time(tstr.strip())
                except ValueError:
                    continue
                scentime.append(t + t_offset)
                scencmd.append(cmd)
        if mergeWithExisting:
            merged = sorted(zip(self.scentime + scentime,
                                range(len(self.scencmd) + len(scencmd)),
                                self.scencmd + scencmd))
            self.scentime = [m[0] for m in merged]
            self.scencmd = [m[2] for m in merged]
        else:
            self.scentime, self.scencmd = scentime, scencmd
        return True, None

    def set_scendata(self, scentime, scencmd):
        """Install a pre-parsed scenario (BATCH farm-out piece,
        simulation.py:225-230)."""
        self.scentime = list(scentime)
        self.scencmd = list(scencmd)

    def _find_scn(self, fname: str) -> Optional[str]:
        if not fname.lower().endswith(".scn"):
            fname += ".scn"
        from .. import settings
        cands = [fname, os.path.join(self.scenario_path, fname)]
        # the reference scenario library ships ~90 .scn files; search it
        # after the local dir (settings defaults it when mounted)
        if settings.ref_scenario_path:
            cands.append(os.path.join(settings.ref_scenario_path, fname))
        for c in cands:
            if os.path.isfile(c):
                return c
        # case-insensitive fallback (the library mixes .scn and .SCN)
        for d in (self.scenario_path, settings.ref_scenario_path):
            if d and os.path.isdir(d):
                low = fname.lower()
                for entry in os.listdir(d):
                    p = os.path.join(d, entry)
                    if entry.lower() == low and os.path.isfile(p):
                        return p
        return None

    def checkfile(self, simt: float):
        """Stack all scenario commands that are due (stack.py:1177-1183)."""
        while self.scencmd and self.scentime[0] <= simt + 1e-9:
            self.stack(self.scencmd.pop(0))
            self.scentime.pop(0)

    def next_trigger_time(self) -> Optional[float]:
        return self.scentime[0] if self.scentime else None

    def ic(self, fname: str = ""):
        """IC: reset and replay a scenario (stack.py:1139-1174)."""
        self.saveclose()
        if fname.upper() == "IC" or fname == "":
            # bare IC reloads the last scenario — by its ORIGINAL path,
            # which may live outside the search dirs
            fname = self.scenfile or self.scenname or "ic"
        ok, msg = self.openfile(fname)
        if not ok:
            return False, msg
        scentime, scencmd = self.scentime, self.scencmd
        self.sim.reset()
        self.scentime, self.scencmd = scentime, scencmd
        # scenname is the STEM, never a path — it is spliced into log
        # filenames (reference stack.py IC does the same strip);
        # scenfile keeps the reload path.
        self.scenfile = fname
        self.scenname = os.path.splitext(os.path.basename(fname))[0]
        return True, f"IC: loaded {fname}"

    def scen(self, name: str, mergetime: Optional[float] = None):
        self.scenname = name
        return True

    def sched_cmd(self, dt_or_time: float, cmdline: str, relative: bool):
        """DELAY/SCHEDULE: insert a command into the timed queue
        (stack.py:1005-1022)."""
        t = self.sim.simt + dt_or_time if relative else dt_or_time
        i = 0
        while i < len(self.scentime) and self.scentime[i] <= t:
            i += 1
        self.scentime.insert(i, t)
        self.scencmd.insert(i, cmdline)
        return True

    # ---------------------------------------------------------------- SAVEIC
    def saveic(self, fname: Optional[str] = None):
        """Snapshot current traffic as CRE/route commands + record onward
        commands (stack.py:1185-1321, condensed)."""
        if fname is None:
            return False, "SAVEIC needs a filename"
        if not fname.lower().endswith(".scn"):
            fname += ".scn"
        os.makedirs(self.scenario_path, exist_ok=True)
        path = os.path.join(self.scenario_path, fname)
        self.savefile = open(path, "w")
        self.saveict0 = self.sim.simt
        from ..ops import aero
        import numpy as np
        traf = self.sim.traf
        st = traf.state
        for slot, acid in enumerate(traf.ids):
            if acid is None:
                continue
            lat = float(st.ac.lat[slot])
            lon = float(st.ac.lon[slot])
            hdg = float(st.ac.hdg[slot])
            alt = float(st.ac.alt[slot])
            cas = float(st.ac.cas[slot])
            self.savecmd(
                f"CRE {acid} {traf.types[slot]} {lat:.6f} {lon:.6f} "
                f"{hdg:.1f} {alt / aero.ft:.0f} {cas / aero.kts:.0f}")
            r = self.sim.routes.routes.get(slot)
            if r is not None:
                for w in range(r.nwp):
                    altarg = f" {r.alt[w] / aero.ft:.0f}" if r.alt[w] >= 0 else ""
                    self.savecmd(f"ADDWPT {acid} {r.lat[w]:.6f} {r.lon[w]:.6f}"
                                 + altarg)
        return True, f"SAVEIC: recording to {path}"

    def savecmd(self, cmdline: str):
        if self.savefile is None:
            return
        t = self.sim.simt - self.saveict0
        h = int(t // 3600)
        m = int((t % 3600) // 60)
        s = t % 60
        self.savefile.write(f"{h:02d}:{m:02d}:{s:05.2f}>{cmdline}\n")

    def saveclose(self):
        if self.savefile is not None:
            self.savefile.close()
            self.savefile = None
        return True

    def reset(self):
        self.saveclose()
        self.cmdstack = []
        self.scentime, self.scencmd = [], []


# Commands never recorded by SAVEIC (reference stack.py:129-131
# defexcl: display commands and aircraft creation — the saveic snapshot
# already reconstructs the live fleet, and the reference additionally
# skips later CRE/MCRE/TRAFGEN by default)
SAVEIC_EXCLUDE = {"SAVEIC", "IC", "RESET", "QUIT", "STOP", "OP", "HOLD",
                  "PAUSE", "FF", "BENCHMARK", "SCEN", "PCALL",
                  "PAN", "ZOOM", "POS", "INSEDIT", "CALC",
                  "CRE", "MCRE", "TRAFGEN"}
