"""State-integrity guard: chunk-edge response to in-scan finite trips.

Detection lives inside the device chunk (core/step.run_steps_checked: an
isfinite all-reduce folded into the lax.scan carry reports the first bad
step index).  This module is the HOST side: when a chunk trips, identify
the poisoned aircraft, log them (FAULTLOG event logger + echo), and
apply the recovery policy:

* ``quarantine`` (default) — delete the non-finite aircraft (mask flip,
  slot identity preserved for the rest of the fleet) and scrub any
  non-finite leftovers from the state arrays, so the run continues with
  the healthy fleet.
* ``rollback``   — restore the newest snapshot-ring checkpoint
  (simulation/snapshot.SnapshotRing), then ALSO quarantine the aircraft
  that were poisoned — rollback without quarantine would replay
  straight back into the same fault.  Falls back to plain quarantine
  when the ring is empty.
* ``halt``       — pause the sim and keep the corrupt state untouched
  for debugging (the only policy that does not scrub).

Every trip is recorded in ``guard.trips`` (host-visible for tests and
reports) and echoed to the issuing client.
"""
import numpy as np


class IntegrityGuard:
    def __init__(self, sim):
        self.sim = sim
        from .. import settings
        self.enabled = bool(getattr(settings, "guard_enabled", True))
        self.policy = str(getattr(settings, "guard_policy",
                                  "quarantine")).lower()
        self.trips = []           # [{simt, bad_step, ids, action}]
        # per-sim registry: W multi-world sims keep separate FAULTLOGs
        self.logger = sim.datalog.define_event(
            "FAULTLOG", "State-integrity guard trips: acid, action")

    def reset(self):
        self.trips.clear()

    def set_policy(self, policy: str) -> bool:
        policy = policy.lower()
        if policy not in ("quarantine", "rollback", "halt"):
            return False
        self.policy = policy
        return True

    # ------------------------------------------------------------ response
    def bad_slots(self):
        """Live slots with a non-finite guarded field (host-side scan)."""
        from ..core.step import GUARD_FIELDS
        ac = self.sim.traf.state.ac
        live = np.asarray(ac.active)
        bad = np.zeros(live.shape, bool)
        for f in GUARD_FIELDS:
            bad |= ~np.isfinite(np.asarray(getattr(ac, f)))
        return np.nonzero(bad & live)[0].tolist()

    def scrub(self):
        """Replace every non-finite float in the state pytree with 0 so
        stale corruption in deactivated rows can never propagate through
        arithmetic masking (NaN * 0 == NaN)."""
        import jax
        import jax.numpy as jnp

        def fix(x):
            if jnp.issubdtype(x.dtype, jnp.floating):
                return jnp.where(jnp.isfinite(x), x, jnp.zeros_like(x))
            return x

        traf = self.sim.traf
        traf.state = jax.tree.map(fix, traf.state)

    def trip(self, bad_step: int, chunk: int):
        """Handle one tripped chunk; called by Simulation.step at the
        chunk edge with the in-scan first-bad-step index."""
        sim = self.sim
        slots = self.bad_slots()
        ids = [sim.traf.ids[s] for s in slots
               if sim.traf.ids[s] is not None]
        action = self.policy
        if self.policy == "halt":
            sim.pause()
        elif self.policy == "rollback" and len(sim.snap_ring):
            ok, msg = sim.snap_ring.rollback(sim)
            if ok:
                action = "rollback+quarantine"
                self._delete_ids(ids)
            else:                       # corrupt ring entry: degrade
                action = "quarantine"
                self._delete_slots(self.bad_slots())
            self.scrub()
        else:
            action = "quarantine"
            self._delete_slots(slots)
            self.scrub()
        rec = dict(simt=sim.simt, bad_step=int(bad_step), chunk=int(chunk),
                   ids=ids, action=action)
        self.trips.append(rec)
        names = ",".join(ids) if ids else "<none identified>"
        sim.scr.echo(f"INTEGRITY GUARD: non-finite state at step "
                     f"{bad_step}/{chunk} of the chunk — {action} "
                     f"[{names}]")
        if self.logger.active:
            self.logger.log(sim, ids or ["-"], [action])
        # Observability: count the trip, mark it on the flight-recorder
        # timeline, and dump the ring so the spans LEADING UP TO the
        # incident survive it (throttled; docs/OBSERVABILITY.md).
        sim.obs.counter("sim_guard_trips").inc()
        sim.recorder.instant("guard_trip", bad_step=int(bad_step),
                             chunk=int(chunk), action=action,
                             nbad=len(ids), world=sim.world_tag)
        sim.recorder.auto_dump("guard_trip")
        return rec

    def mesh_trip(self, action: str, **extra):
        """Record a structured mesh-epoch event (``mesh_lost`` /
        ``resharded``) in the trip log.  Unlike ``trip`` this does not
        touch aircraft state — the mesh-recovery layer
        (simulation/sim._handle_mesh_lost) owns the response; the guard
        just gives the event the same audit trail (``guard.trips`` +
        FAULTLOG) as every other fault class."""
        sim = self.sim
        rec = dict(simt=float(sim.simt_planned), bad_step=-1,
                   chunk=int(sim._step_count), ids=[],
                   action=str(action), source="mesh_guard", **extra)
        self.trips.append(rec)
        if self.logger.active:
            self.logger.log(sim, ["-"], [str(action)])
        # Same observability treatment as state trips: the mesh_lost /
        # resharded pair brackets the recovery on the merged timeline.
        sim.obs.counter("sim_mesh_trips").inc()
        tags = {k: v for k, v in extra.items()
                if isinstance(v, (int, float, str, bool, list))}
        sim.recorder.instant(str(action), world=sim.world_tag, **tags)
        sim.recorder.auto_dump("mesh_trip")
        return rec

    def _delete_slots(self, slots):
        if slots:
            self.sim.traf.delete(list(slots))

    def _delete_ids(self, ids):
        """Delete by callsign — slot numbers may differ after rollback."""
        slots = [self.sim.traf.id2idx(a) for a in ids]
        self._delete_slots([s for s in slots
                            if isinstance(s, int) and s >= 0])
