"""The FAULT stack command: chaos injection on a running sim/worker.

Usage (stack/commands.py registers it):

  FAULT                      status: guard, ring, transport faults, trips
  FAULT NAN [acid]           poison an aircraft's state with NaN
  FAULT INF [acid]           poison an aircraft's state with +Inf
  FAULT BITFLIP [STATE|PAYLOAD] [acid|bit]   flip ONE bit: STATE flips
                             a low mantissa bit of one aircraft's
                             latitude (stays finite — invisible to the
                             guard, caught ONLY by the SDC fingerprint
                             comparison); PAYLOAD corrupts the shipped
                             fingerprint word until RESET (wire model)
  FAULT GUARD ON/OFF         enable/disable the integrity guard
  FAULT GUARD QUARANTINE/ROLLBACK/HALT   set the recovery policy
  FAULT RING [depth] [dt]    report / configure the snapshot ring
  FAULT DROP p               drop outgoing event frames with prob p
  FAULT DUP p                duplicate outgoing event frames with prob p
  FAULT DELAY sec            delay outgoing event frames by sec
  FAULT NETOFF               remove transport faults
  FAULT STALL sec            stall this worker's event loop for sec
  FAULT STRAGGLE factor      throttle the chunk loop (factor extra
                             wall-s per sim-s): the merely-slow worker
  FAULT STRAGGLE STALL [sec] freeze progress (heartbeats keep flowing)
                             [for sec]; server-side hedging recovers
  FAULT STRAGGLE OFF         clear the straggle fault
  FAULT KILL                 SIGKILL this worker (no goodbye)
  FAULT KILLSERVER [delay]   SIGKILL the BROKER process [after delay s]
                             (head-node loss model): with broker HA
                             (network/ha.py) the warm standby takes the
                             lease over and the sweep continues; without
                             it, --resume-batch recovers at restart
  FAULT PREEMPT [delay]      preemption notice (SIGTERM model): drain
                             the chunk, checkpoint, notify, exit
  FAULT MESHKILL [group]     mark one device group of the active mesh
                             dead (host-loss model): the MeshGuard trips
                             mesh_lost at the next chunk dispatch and
                             the sim re-forms a survivor mesh
  FAULT PARTITION [OFF]      heartbeat-only network partition: PONGs
                             dropped, completions still delivered
  FAULT LOADSPIKE n [rate]   flood the server with n synthetic BATCH
                             pieces ([rate]/s; default one burst): the
                             queue-flood model — replay/exactly-once
                             accounting ignores the filler; admission
                             control + mitigation shedding respond
  FAULT SNAPTRUNC fname [keep]  truncate a snapshot file (torn write)
  FAULT LIST                 guard trip history

Transport faults need a networked worker (``sim.node``); on a detached
sim they return a command error instead of injecting nothing silently.
"""
from . import injectors


def _node(sim):
    """The sim's network endpoint, or None when there is no event
    socket to degrade (detached/embedded sims)."""
    node = getattr(sim, "node", None)
    return node if getattr(node, "event_io", None) is not None else None


def _status(sim):
    g = sim.guard
    lines = [f"guard: {'ON' if g.enabled else 'OFF'} "
             f"(policy {g.policy}), trips: {len(g.trips)}",
             f"ring: {len(sim.snap_ring)}/{sim.snap_ring.depth} "
             f"snapshots, dt={sim.snap_ring.dt:g} s"]
    node = _node(sim)
    sock = getattr(node, "event_io", None)
    if isinstance(sock, injectors.FlakySocket):
        lines.append(f"transport: drop={sock.p_drop:g} dup={sock.p_dup:g} "
                     f"delay={sock.delay_s:g}s (sent {sock.n_sent}, "
                     f"dropped {sock.n_dropped}, duped {sock.n_duped}, "
                     f"delayed {sock.n_delayed})")
    else:
        lines.append("transport: clean")
    if isinstance(sock, injectors.FlakySocket) and sock.drop_names:
        names = ",".join(n.decode("ascii", "replace")
                         for n in sock.drop_names)
        lines.append(f"partition: dropping [{names}] "
                     f"({sock.n_name_dropped} suppressed)")
    if getattr(sim, "straggle_stall", False):
        lines.append("straggle: STALLED (progress frozen)")
    elif getattr(sim, "straggle_factor", 0.0) > 0:
        lines.append(f"straggle: throttled +{sim.straggle_factor:g} "
                     f"wall s per sim s")
    mh = sim.mesh_health()
    if mh["mode"] != "off" or mh["epoch"] > 0:
        lines.append(f"mesh: epoch {mh['epoch']}, {mh['devices']} "
                     f"device(s), mode {mh['mode']}"
                     + (" [degraded]" if mh["degraded"] else ""))
    return True, "\n".join(lines)


def fault_command(sim, *args):
    if not args:
        return _status(sim)
    sub = str(args[0]).upper()
    rest = [str(a) for a in args[1:]]

    if sub in ("NAN", "INF"):
        value = float("nan") if sub == "NAN" else float("inf")
        try:
            slot, acid = injectors.inject_nonfinite(
                sim, rest[0] if rest else None, value)
        except ValueError as e:
            return False, str(e)
        return True, (f"FAULT: injected {sub} into {acid} (slot {slot}) — "
                      f"guard {'armed' if sim.guard.enabled else 'OFF'}")

    if sub == "BITFLIP":
        which = rest[0].upper() if rest else "STATE"
        if which == "PAYLOAD":
            try:
                bit = int(float(rest[1])) if len(rest) > 1 else 2
            except ValueError:
                return False, "FAULT BITFLIP PAYLOAD [bit]"
            mask = injectors.inject_bitflip(sim, "payload", bit=bit)
            return True, (f"FAULT: fingerprint wire corruption armed — "
                          f"shipped words XOR {mask:#010x} until RESET")
        acid = None
        if which == "STATE":
            acid = rest[1] if len(rest) > 1 else None
        else:
            acid = rest[0]         # FAULT BITFLIP <acid> shorthand
        try:
            slot, acid, old, new = injectors.inject_bitflip(
                sim, "state", acid=acid)
        except ValueError as e:
            return False, str(e)
        return True, (f"FAULT: flipped one mantissa bit of {acid} "
                      f"(slot {slot}) lat {old!r} -> {new!r} — finite, "
                      f"guard-invisible; only the SDC fingerprint "
                      f"comparison can catch it")

    if sub == "GUARD":
        if not rest:
            return True, (f"guard is {'ON' if sim.guard.enabled else 'OFF'}"
                          f" (policy {sim.guard.policy})")
        arg = rest[0].upper()
        if arg in ("ON", "TRUE", "1"):
            sim.guard.enabled = True
            return True, "guard ON"
        if arg in ("OFF", "FALSE", "0"):
            sim.guard.enabled = False
            return True, "guard OFF"
        if sim.guard.set_policy(arg):
            return True, f"guard policy {sim.guard.policy}"
        return False, "FAULT GUARD ON/OFF/QUARANTINE/ROLLBACK/HALT"

    if sub == "RING":
        ring = sim.snap_ring
        if rest:
            try:
                depth = int(float(rest[0]))
                if len(rest) > 1:
                    ring.dt = float(rest[1])
            except ValueError:
                return False, "FAULT RING [depth] [dt]"
            if depth != ring.depth:
                import collections
                ring.depth = max(1, depth)
                ring._ring = collections.deque(ring._ring,
                                               maxlen=ring.depth)
        ts = ", ".join(f"{t:.1f}" for t in ring.simts) or "-"
        return True, (f"ring: depth {ring.depth}, dt {ring.dt:g} s, "
                      f"held simt [{ts}]")

    if sub in ("DROP", "DUP", "DELAY"):
        node = _node(sim)
        if node is None:
            return False, f"FAULT {sub}: no network node (detached sim)"
        try:
            p = float(rest[0]) if rest else 0.0
        except ValueError:
            return False, f"FAULT {sub} value"
        kw = {"DROP": "p_drop", "DUP": "p_dup", "DELAY": "delay_s"}[sub]
        from .. import settings
        flaky = injectors.install_flaky(
            node, seed=int(getattr(settings, "fault_seed", 0)), **{kw: p})
        return True, (f"FAULT: event transport drop={flaky.p_drop:g} "
                      f"dup={flaky.p_dup:g} delay={flaky.delay_s:g}s")

    if sub in ("NETOFF", "OFF"):
        node = _node(sim)
        if node is not None and injectors.remove_flaky(node):
            return True, "FAULT: transport faults removed"
        return True, "FAULT: transport already clean"

    if sub == "STALL":
        try:
            sec = float(rest[0]) if rest else 1.0
        except ValueError:
            return False, "FAULT STALL seconds"
        injectors.stall(sec)
        return True, f"FAULT: stalled {sec:g} s"

    if sub == "STRAGGLE":
        arg = rest[0].upper() if rest else ""
        if arg in ("OFF", "0"):
            injectors.straggle(sim)
            return True, "FAULT: straggle cleared"
        if arg == "STALL":
            try:
                dur = float(rest[1]) if len(rest) > 1 else 0.0
            except ValueError:
                return False, "FAULT STRAGGLE STALL [seconds]"
            injectors.straggle(sim, stall_progress=True, stall_s=dur)
            return True, ("FAULT: progress stalled"
                          + (f" for {dur:g} s" if dur > 0 else "")
                          + " — heartbeats keep flowing; the server "
                            "hedges the piece after straggler_timeout")
        try:
            factor = float(arg) if arg else 1.0
        except ValueError:
            return False, "FAULT STRAGGLE factor | STALL [s] | OFF"
        injectors.straggle(sim, factor=factor)
        return True, (f"FAULT: chunk loop throttled — +{factor:g} wall "
                      f"s per sim s")

    if sub == "KILL":
        injectors.kill_self()          # no return: SIGKILL

    if sub == "KILLSERVER":
        node = _node(sim)
        pid = getattr(node, "server_pid", None)
        if not pid:
            return False, ("FAULT KILLSERVER: no broker pid known "
                           "(detached sim, or the server predates the "
                           "pid-carrying REGISTER ack)")
        try:
            delay = float(rest[0]) if rest else 0.0
        except ValueError:
            return False, "FAULT KILLSERVER [delay_s]"
        injectors.kill_server(pid, delay)
        return True, (f"FAULT: SIGKILL broker pid {pid}"
                      + (f" in {delay:g} s" if delay > 0 else "")
                      + " — the WAL is append-only, so a warm standby "
                        "(or --resume-batch) recovers the sweep "
                        "exactly-once")

    if sub == "PREEMPT":
        try:
            delay = float(rest[0]) if rest else 0.0
        except ValueError:
            return False, "FAULT PREEMPT [delay_s]"
        injectors.preempt(sim, delay)
        return True, (f"FAULT: preemption notice"
                      + (f" in {delay:g} s" if delay > 0 else "")
                      + " — the node will drain the current chunk, "
                        "write a final checkpoint and exit")

    if sub == "MESHKILL":
        if sim.shard_mode == "off" or sim.shard_mesh is None:
            return False, "FAULT MESHKILL: no active mesh (SHARD first)"
        try:
            group = int(float(rest[0])) if rest else 1
        except ValueError:
            return False, "FAULT MESHKILL [group]"
        try:
            devs = sim.mesh_guard.kill_group(group)
        except ValueError as e:
            return False, f"FAULT MESHKILL: {e}"
        return True, (f"FAULT: device group {group} ({len(devs)} "
                      f"device(s)) marked dead — mesh_lost trips at "
                      f"the next chunk dispatch")

    if sub == "PARTITION":
        node = _node(sim)
        if node is None:
            return False, "FAULT PARTITION: no network node (detached sim)"
        if rest and rest[0].upper() in ("OFF", "0"):
            injectors.partition(node, names=())
            return True, "FAULT: partition healed (heartbeats flowing)"
        flaky = injectors.partition(node)
        names = ",".join(n.decode("ascii", "replace")
                         for n in flaky.drop_names)
        return True, (f"FAULT: network partition — dropping [{names}]; "
                      f"worker alive, completions still delivered")

    if sub == "LOADSPIKE":
        node = _node(sim)
        if node is None:
            return False, "FAULT LOADSPIKE: no network node (detached sim)"
        try:
            n = int(float(rest[0])) if rest else 16
            rate = float(rest[1]) if len(rest) > 1 else 0.0
        except ValueError:
            return False, "FAULT LOADSPIKE n [rate]"
        sent = injectors.load_spike(node, n, rate)
        return True, (f"FAULT: load spike — {sent} synthetic piece(s) "
                      + (f"at {rate:g}/s" if rate > 0 else "in one burst")
                      + "; over-limit submissions bounce as BATCHREJECTED")

    if sub == "SNAPTRUNC":
        if not rest:
            return False, "FAULT SNAPTRUNC filename [keep_fraction]"
        import os
        fname = rest[0]
        if not fname.lower().endswith(".snap"):
            fname += ".snap"
        if not os.path.isfile(fname):
            return False, f"{fname}: not found"
        keep = float(rest[1]) if len(rest) > 1 else 0.5
        size = injectors.truncate_file(fname, keep)
        return True, f"FAULT: truncated {fname} to {size} bytes"

    if sub == "LIST":
        if not sim.guard.trips:
            return True, "no guard trips"
        return True, "\n".join(
            f"simt {t['simt']:.2f}: step {t['bad_step']}/{t['chunk']} "
            f"{t['action']} [{','.join(t['ids']) or '-'}]"
            for t in sim.guard.trips)

    return False, ("FAULT NAN/INF [acid] | BITFLIP [STATE|PAYLOAD] | "
                   "GUARD .. | RING .. | DROP/DUP/"
                   "DELAY p | NETOFF | STALL s | STRAGGLE f/STALL/OFF | "
                   "KILL | KILLSERVER [s] | PREEMPT [s] | MESHKILL [g] "
                   "| PARTITION [OFF] | "
                   "LOADSPIKE n [rate] | SNAPTRUNC f | LIST")
