"""Chaos injectors: state poisoning, flaky transport, process faults.

Each injector models ONE fault class from the failure model in
docs/FAULT_TOLERANCE.md; the FAULT stack command (harness.py) binds them
to a running sim, and tests/test_chaos.py drives them directly.  All are
deterministic under a seeded RNG so chaos runs replay.
"""
import os
import signal
import threading
import time

import numpy as np


# --------------------------------------------------------- state poisoning
def inject_nonfinite(sim, acid=None, value=float("nan"), fields=None):
    """Poison guarded state fields of one aircraft with NaN/Inf.

    Models silent device-state corruption (bad wind data, a kernel bug,
    a bitflip): the value is written straight into the device pytree, so
    the ONLY thing that can catch it is the in-scan integrity guard.
    Returns (slot, acid) of the poisoned aircraft.
    """
    traf = sim.traf
    traf.flush()
    if acid:
        slot = traf.id2idx(str(acid))
        if not isinstance(slot, int) or slot < 0:
            raise ValueError(f"{acid}: aircraft not found")
    else:
        live = [i for i, v in enumerate(traf.ids) if v is not None]
        if not live:
            raise ValueError("no aircraft to poison")
        slot = live[0]
    from ..core.step import GUARD_FIELDS
    fields = tuple(fields or GUARD_FIELDS[:1] + ("tas",))
    st = traf.state
    ac = st.ac
    upd = {f: getattr(ac, f).at[slot].set(value) for f in fields}
    traf.state = st.replace(ac=ac.replace(**upd))
    return slot, traf.ids[slot]


def inject_bitflip(sim, which="state", acid=None, bit=2):
    """Flip ONE bit — the silent-data-corruption model (ISSUE-17).

    ``which='state'``: flip a low mantissa bit of one live aircraft's
    latitude IN the device state.  The value stays finite, so the
    in-scan integrity guard (``isfinite``) can never catch it — only
    the state-fingerprint comparison across redundant executions does.
    Returns ``(slot, acid, old, new)``.

    ``which='payload'``: corrupt the fingerprint ON THE WIRE — every
    shipped summary word is XORed with ``1 << bit`` until the next
    RESET, while the device state and fold stay untouched (the
    readback/transport-corruption model).  Returns the active mask.
    """
    bit = int(bit)
    if str(which).lower().startswith("payload"):
        sim._fp_corrupt_mask ^= (1 << (bit % 32)) & 0xFFFFFFFF
        return sim._fp_corrupt_mask
    traf = sim.traf
    traf.flush()
    if acid:
        slot = traf.id2idx(str(acid))
        if not isinstance(slot, int) or slot < 0:
            raise ValueError(f"{acid}: aircraft not found")
    else:
        live = [i for i, v in enumerate(traf.ids) if v is not None]
        if not live:
            raise ValueError("no aircraft to corrupt")
        slot = live[0]
    st = traf.state
    ac = st.ac
    lat = ac.lat
    old = float(np.asarray(lat[slot]))
    width = np.dtype(lat.dtype).itemsize
    u = np.array([old], dtype=lat.dtype)
    iv = u.view({4: np.uint32, 8: np.uint64}[width])
    iv[0] ^= np.asarray(1, iv.dtype) << np.asarray(
        bit % (8 * width), iv.dtype)
    new = float(u[0])
    traf.state = st.replace(ac=ac.replace(lat=lat.at[slot].set(new)))
    return slot, traf.ids[slot], old, new


# --------------------------------------------------------- flaky transport
class FlakySocket:
    """Transport-fault wrapper over a ZMQ socket: drop / duplicate /
    delay outgoing multipart frames with seeded probabilities.

    Installed over a Node/Client event socket by ``FAULT DROP/DUP/
    DELAY``; everything except ``send_multipart`` delegates to the
    wrapped socket, so the endpoint code never knows.  Delayed frames
    are buffered and released by the next send (or an explicit
    ``flush``), modelling reordering-free late delivery.  Counters
    (``n_sent/n_dropped/n_duped/n_delayed``) make the chaos observable.
    """

    def __init__(self, sock, p_drop=0.0, p_dup=0.0, delay_s=0.0, seed=0,
                 drop_names=()):
        self._sock = sock
        self.p_drop = float(p_drop)
        self.p_dup = float(p_dup)
        self.delay_s = float(delay_s)
        # selective drop by event name (the network-partition model:
        # heartbeats lost, everything else delivered) — frame layout is
        # [route..., name, payload], so the name rides frames[-2]
        self.drop_names = tuple(drop_names)
        self._rng = np.random.default_rng(seed)
        self._held = []            # [(release_time, frames, kwargs)]
        self.n_sent = 0
        self.n_dropped = 0
        self.n_duped = 0
        self.n_delayed = 0
        self.n_name_dropped = 0

    def __getattr__(self, name):
        return getattr(self._sock, name)

    @property
    def wrapped(self):
        return self._sock

    def flush(self, force=False):
        """Release every held frame whose delay has expired (all of
        them with ``force`` — the uninstall path must not lose frames
        that were merely late)."""
        now = time.monotonic()
        due = [h for h in self._held if force or h[0] <= now]
        self._held = [] if force else [h for h in self._held
                                       if h[0] > now]
        for _, frames, kwargs in due:
            self._sock.send_multipart(frames, **kwargs)
            self.n_sent += 1

    def send_multipart(self, frames, **kwargs):
        self.flush()
        if self.drop_names:
            fl = list(frames)
            name = fl[-2] if len(fl) >= 2 else (fl[0] if fl else b"")
            if name in self.drop_names:
                self.n_name_dropped += 1
                return
        if self.p_drop > 0 and self._rng.random() < self.p_drop:
            self.n_dropped += 1
            return
        if self.delay_s > 0:
            self._held.append((time.monotonic() + self.delay_s,
                               list(frames), kwargs))
            self.n_delayed += 1
            return
        self._sock.send_multipart(frames, **kwargs)
        self.n_sent += 1
        if self.p_dup > 0 and self._rng.random() < self.p_dup:
            self._sock.send_multipart(frames, **kwargs)
            self.n_duped += 1


def install_flaky(endpoint, attr="event_io", **kw):
    """Wrap ``endpoint.<attr>`` in a FlakySocket (idempotent: re-wrapping
    updates the probabilities on the existing wrapper)."""
    sock = getattr(endpoint, attr)
    if isinstance(sock, FlakySocket):
        sock.p_drop = float(kw.get("p_drop", sock.p_drop))
        sock.p_dup = float(kw.get("p_dup", sock.p_dup))
        sock.delay_s = float(kw.get("delay_s", sock.delay_s))
        if "drop_names" in kw:
            sock.drop_names = tuple(kw["drop_names"])
        return sock
    flaky = FlakySocket(sock, **kw)
    setattr(endpoint, attr, flaky)
    return flaky


def partition(endpoint, names=(b"PONG",), attr="event_io"):
    """Heartbeat-only network partition (FAULT PARTITION): the worker
    stays alive and keeps computing, its completions and state changes
    still arrive, but its PING replies are silently dropped — the
    half-dead link the server cannot distinguish from a dead worker.
    ``names=()`` heals the partition (other flaky settings survive)."""
    return install_flaky(endpoint, attr=attr, drop_names=tuple(names))


def remove_flaky(endpoint, attr="event_io"):
    """Undo ``install_flaky``: flush ALL held frames (even not-yet-due
    ones — restoring the transport must not lose them), restore the
    raw socket."""
    sock = getattr(endpoint, attr)
    if isinstance(sock, FlakySocket):
        sock.delay_s = 0.0
        sock.flush(force=True)
        setattr(endpoint, attr, sock.wrapped)
        return True
    return False


# ----------------------------------------------------------- process faults
def kill_self():
    """SIGKILL the current process — the poison-pill / OOM-killer model.
    No goodbye, no linger: the server must detect the death via child
    exit / PING silence and requeue this worker's BATCH piece."""
    os.kill(os.getpid(), signal.SIGKILL)


def kill_server(pid, delay_s: float = 0.0):
    """SIGKILL the BROKER process after ``delay_s`` — the head-node
    loss model (broker HA, network/ha.py).  The pid comes from the
    server's REGISTER ack (node.server_pid).  No goodbye, no journal
    shutdown marker: the warm standby must notice via lease silence,
    take over the sweep journal-fenced, and surviving workers must
    re-discover and re-REGISTER with their in-flight pieces."""
    pid = int(pid)
    if delay_s and float(delay_s) > 0:
        t = threading.Timer(float(delay_s), os.kill,
                            args=(pid, signal.SIGKILL))
        t.daemon = True
        t.start()
        return t
    os.kill(pid, signal.SIGKILL)
    return None


def preempt(sim, delay_s: float = 0.0):
    """Deliver a preemption notice to this sim after ``delay_s`` —
    the SIGTERM-from-the-scheduler model (spot/preemptible capacity
    being reclaimed).  Raises ``sim.preempt_requested``; the owning
    node drains the in-flight chunk, writes a final checksummed
    checkpoint, notifies the server and exits cleanly
    (simulation/simnode._preempt_shutdown) — an embedded sim
    checkpoints and pauses.  A real out-of-process SIGTERM lands in
    the same path via the node's signal handler."""
    if delay_s and float(delay_s) > 0:
        t = threading.Timer(float(delay_s), sim.request_preempt)
        t.daemon = True
        t.start()
        return t
    sim.request_preempt()
    return None


def stall(seconds: float):
    """Block the calling thread — the stuck-event-loop model (GC pause,
    NFS hang, a runaway host callback).  The node watchdog
    (network/node.py) is the detector."""
    time.sleep(float(seconds))


def straggle(sim, factor: float = 0.0, stall_progress: bool = False,
             stall_s: float = 0.0):
    """The merely-slow / stuck-but-alive worker model (the dominant
    throughput killer in multi-GPU traffic simulation, arXiv:2406.08496
    load imbalance) — the fault class PING silence can NOT detect,
    because the event loop keeps running and heartbeats keep flowing.

    ``factor`` throttles the chunk loop (each sim second costs
    ``factor`` extra wall seconds), sinking this worker's progress
    rate below the fleet median.  ``stall_progress`` freezes progress
    outright (the chunk loop spins without advancing simt) — with
    ``stall_s`` set, a timer releases the stall after that long.  The
    server's progress-heartbeat straggler detector is the detector;
    speculative hedging is the response.  ``factor=0`` and
    ``stall_progress=False`` clears the fault.  Both settings survive
    sim RESET on purpose: they model host slowness, not scenario
    state."""
    sim.straggle_factor = max(0.0, float(factor))
    sim.straggle_stall = bool(stall_progress)
    sim._straggle_debt = 0.0       # a new injection starts clean
    # generation stamp: a timed stall's auto-clear must not fire into a
    # LATER straggle injection (re-issuing an indefinite stall while an
    # old timer is pending would otherwise end it early)
    gen = getattr(sim, "_straggle_gen", 0) + 1
    sim._straggle_gen = gen
    if stall_progress and stall_s and float(stall_s) > 0:
        def _clear():
            if getattr(sim, "_straggle_gen", 0) == gen:
                sim.straggle_stall = False
        t = threading.Timer(float(stall_s), _clear)
        t.daemon = True
        t.start()
        return t
    return None


_spike_seq = [0]                   # distinct piece content per injection


def load_spike(node, n, rate=0.0, tag="LS"):
    """Flood the server with ``n`` SYNTHETIC BATCH pieces — the
    queue-flood / thundering-herd model that drives the admission and
    load-shedding path (server-side mitigation is the response).

    Pieces are tiny self-draining sweeps (SCEN/CRE/FF/HOLD, like a real
    mini-sweep) submitted with ``synthetic: true``: the journal marks
    their ``queued`` records so replay's exactly-once accounting skips
    them — a resumed sweep is never owed load-spike noise.  Over-limit
    submissions come back as normal ``BATCHREJECTED`` refusals (echoed
    by the node), which is precisely the overload being modelled.

    ``rate`` pieces/second paces the flood with one submission per
    piece (``rate<=0``: one burst submission carrying all n).  Pacing
    sleeps on the calling thread — the injecting worker's event loop
    stalls for ``n/rate`` seconds, capped at 30 s — so keep paced
    spikes short; the burst mode costs nothing.

    Returns the number of pieces submitted."""
    _spike_seq[0] += 1
    nonce = f"{os.getpid():x}-{_spike_seq[0]:x}"
    n = max(1, int(n))
    rate = float(rate)

    def _piece(i):
        name = f"{tag}{nonce}-{i:04d}"
        return ([0.0, 0.0, 0.0, 60.0],
                [f"SCEN {name}",
                 f"CRE {name} B744 {40 + (i % 20)} 4 90 FL200 250",
                 "FF", "HOLD"])

    if rate <= 0:
        scentime, scencmd = [], []
        for i in range(n):
            t, c = _piece(i)
            scentime += t
            scencmd += c
        node.send_event(b"BATCH", {"scentime": scentime,
                                   "scencmd": scencmd,
                                   "synthetic": True})
        return n
    n = min(n, max(1, int(rate * 30.0)))   # cap the loop-stall at 30 s
    for i in range(n):
        t, c = _piece(i)
        node.send_event(b"BATCH", {"scentime": t, "scencmd": c,
                                   "synthetic": True})
        if i + 1 < n:
            time.sleep(1.0 / rate)
    return n


# ------------------------------------------------------------- file faults
def truncate_file(fname: str, keep_fraction: float = 0.5) -> int:
    """Truncate a file (snapshot, log) to a fraction of its size —
    the torn-write / disk-full model.  Returns the new size."""
    size = os.path.getsize(fname)
    new = int(size * float(keep_fraction))
    with open(fname, "r+b") as f:
        f.truncate(new)
    return new
