"""Fault-injection harness + state-integrity guard (chaos engineering).

A production fleet must survive in-sim corruption (NaN/Inf propagating
through the vmapped step), poison-pill scenarios that crash workers in a
loop, and flaky transport.  This package provides both sides of that
story:

* ``guard``     — the IntegrityGuard the Simulation consults at chunk
                  edges: detect (in-scan isfinite carry, core/step.py),
                  then quarantine the poisoned aircraft or roll the
                  whole state back to a snapshot-ring checkpoint.
* ``injectors`` — the chaos toolbox: NaN/Inf-in-state, dropped/delayed/
                  duplicated ZMQ frames, kill -9 the worker, stalled
                  event loops, truncated snapshot files.
* ``harness``   — the FAULT stack command binding the injectors to a
                  running sim/worker, driving the chaos test suite
                  (tests/test_chaos.py, ``make chaos``).

The recovery matrix (fault x detection x response x test) is documented
in docs/FAULT_TOLERANCE.md.
"""
from .guard import IntegrityGuard                      # noqa: F401
from .injectors import (FlakySocket, inject_nonfinite,  # noqa: F401
                        truncate_file)
from .harness import fault_command                     # noqa: F401
