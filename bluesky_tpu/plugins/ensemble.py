"""Device-side Monte-Carlo ensembles: ENSEMBLE n time [spread].

The reference parallelizes Monte-Carlo studies as a PROCESS farm (the
server's BATCH split, network/server.py) — one OS process per replica.
This plugin is the TPU-first counterpart with no reference equivalent:
the CURRENT traffic scene is replicated on-device with per-replica
initial-condition jitter and stepped as ONE vmapped SPMD program
(``parallel.sharding.ensemble_step_fn``), so a 64-replica study of a
500-aircraft scene costs one kernel launch per chunk instead of 64
processes.  On a multi-device mesh the replicas shard over the 'ens'
axis with zero cross-device traffic.

Usage from the stack:

    CRE ... / IC scenario.scn        # set up the scene
    ENSEMBLE 32 60 500               # 32 replicas, 60 sim-s, 500 m jitter

Reports conflict/LoS count statistics across the ensemble — the
uncertainty band the reference MC studies compute from BATCH logs.
"""
import numpy as np


def init_plugin(sim):
    ens = Ensemble(sim)
    config = {
        "plugin_name": "ENSEMBLE",
        "plugin_type": "sim",
    }
    stackfunctions = {
        "ENSEMBLE": [
            "ENSEMBLE nreps,time[,spread]",
            "int,float,[float]",
            ens.run,
            "Monte-Carlo the current scene on-device: nreps jittered "
            "replicas stepped as one vmapped program",
        ],
    }
    return config, stackfunctions


class Ensemble:
    MAX_SLOTS = 2_000_000        # nmax*nreps guard (device memory)

    def __init__(self, sim):
        self.sim = sim
        self.last = None         # stats dict of the last run
        self._runs = 0           # per-call entropy for the jitter keys
        self._cache = {}         # (cfg, nreps, nmax, nsteps) -> runner

    def run(self, nreps, tend, spread=500.0):
        import jax
        import jax.numpy as jnp
        from ..parallel import sharding

        sim = self.sim
        nreps = int(nreps)
        n = sim.traf.ntraf
        if n == 0:
            return False, "ENSEMBLE: no traffic in the scene"
        if nreps < 2:
            return False, "ENSEMBLE: need at least 2 replicas"
        nmax = sim.traf.state.nmax
        if nmax * nreps > self.MAX_SLOTS:
            return False, (f"ENSEMBLE: {nreps} x nmax {nmax} exceeds "
                           f"{self.MAX_SLOTS} slots — shrink one")
        # A dense-allocated state carries the [nmax, nmax] pair matrix,
        # which every replica would copy — bound that memory too.
        if sim.traf.state.asas.resopairs.size * nreps > 256_000_000:
            return False, ("ENSEMBLE: the [N,N] pair matrix x nreps "
                           "would exceed device memory — run the sim "
                           "with a tiled allocation "
                           "(Traffic(pair_matrix=False)) for large "
                           "ensembles")
        sim.traf.flush()
        base = sim.traf.state

        # Per-replica initial-condition jitter: gaussian position noise
        # of ``spread`` meters (and ~1 kt speed noise) on active slots —
        # the classic MC-over-uncertainty setup the reference runs as
        # BATCH process replicas.  A run counter folds into the key so
        # repeated ENSEMBLE calls draw fresh replicas.
        self._runs += 1
        key = jax.random.fold_in(
            jax.random.PRNGKey(int(np.asarray(base.rng)[-1])), self._runs)
        keys = jax.random.split(key, nreps)
        act = base.ac.active

        def jitter(state_key):
            # 5-way split: four noise draws + a FRESH stream for the
            # replica's in-sim rng (split is prefix-stable, so reusing
            # state_key would alias the first step's noise keys onto
            # the jitter draws)
            k1, k2, k3, k4, knew = jax.random.split(state_key, 5)
            dtype = base.ac.lat.dtype
            mlat = spread / 111_000.0
            mlon = mlat / jnp.maximum(
                jnp.cos(jnp.radians(base.ac.lat)), 0.2)
            noise = lambda k, s: jax.random.normal(
                k, base.ac.lat.shape, dtype) * s
            ac = base.ac.replace(
                lat=jnp.where(act, base.ac.lat + noise(k1, mlat),
                              base.ac.lat),
                lon=jnp.where(act, base.ac.lon + noise(k2, mlon),
                              base.ac.lon),
                tas=jnp.where(act, base.ac.tas + noise(k3, 0.5),
                              base.ac.tas),
                gs=jnp.where(act, base.ac.gs + noise(k4, 0.5),
                             base.ac.gs))
            return base.replace(ac=ac, rng=knew)

        states = jax.vmap(jitter)(keys)
        # Inherit the sim's FULL config (simdt, noise, ASAS settings);
        # only the replica-hostile pieces change: dense CD above a size
        # threshold becomes tiled, and any aircraft-axis mesh is
        # dropped (replicas shard on 'ens', not 'ac').
        backend = sim.cfg.cd_backend
        if backend == "dense" and nmax > 4096:
            backend = "tiled"
        cfg = sim.cfg._replace(cd_backend=backend, cd_mesh=None)

        # Step in CD-interval chunks, accumulating per-replica peak and
        # time-mean counts — sampling only the final step would miss
        # every conflict that resolves before tend.  The compiled chunk
        # runner is cached across calls (a fresh jit closure per call
        # would recompile the scan every time).
        chunk = max(1, int(round(cfg.asas.dtasas / cfg.simdt)))
        # Cover tend exactly: whole CD-interval chunks plus one
        # remainder chunk (rounding tend to whole chunks could silently
        # simulate up to half a CD interval more or less than asked).
        total = max(1, int(round(float(tend) / cfg.simdt)))
        nchunks, rem = divmod(total, chunk)
        plan = [chunk] * nchunks + ([rem] if rem else [])

        def get_runner(nsteps):
            ck = (cfg, nreps, nmax, nsteps)
            runner = self._cache.get(ck)
            if runner is None:
                mesh = sharding.make_ensemble_mesh(
                    min(nreps, len(jax.devices())))
                runner = sharding.ensemble_step_fn(mesh, cfg,
                                                   nsteps=nsteps)
                if len(self._cache) > 2:    # keep the latest plan only
                    self._cache = {}
                self._cache[ck] = runner
                self._ndev = mesh.devices.size
            return runner

        peak_conf = np.zeros(nreps)
        peak_los = np.zeros(nreps)
        sum_conf = np.zeros(nreps)
        sum_los = np.zeros(nreps)
        for nsteps in plan:
            states = get_runner(nsteps)(states)
            nconf = np.asarray(states.asas.nconf_cur) / 2.0  # pairs
            nlos = np.asarray(states.asas.nlos_cur) / 2.0
            peak_conf = np.maximum(peak_conf, nconf)
            peak_los = np.maximum(peak_los, nlos)
            sum_conf += nconf
            sum_los += nlos
        mean_conf = sum_conf / len(plan)
        mean_los = sum_los / len(plan)

        self.last = dict(nreps=nreps, tend=float(tend),
                         spread=float(spread),
                         peak_conf_mean=float(peak_conf.mean()),
                         peak_conf_std=float(peak_conf.std()),
                         mean_conf_mean=float(mean_conf.mean()),
                         peak_los_mean=float(peak_los.mean()),
                         mean_los_mean=float(mean_los.mean()))
        return True, (
            f"ENSEMBLE {nreps} x {float(tend):.0f}s (jitter "
            f"{float(spread):.0f} m) on {self._ndev} device(s), "
            f"conflict PAIRS sampled each CD interval:\n"
            f"  peak conflicts {peak_conf.mean():.1f} "
            f"+- {peak_conf.std():.1f} "
            f"(min {peak_conf.min():.0f}, max {peak_conf.max():.0f})\n"
            f"  mean conflicts {mean_conf.mean():.2f} "
            f"+- {mean_conf.std():.2f}\n"
            f"  peak LoS       {peak_los.mean():.1f} "
            f"+- {peak_los.std():.1f}")
