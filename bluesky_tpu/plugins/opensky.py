"""Live ADS-B traffic replay from the OpenSky Network REST API.

Parity with the reference ``plugins/opensky.py:34-194``: poll the
``/states/all`` endpoint every interval, create aircraft for new
callsigns, MOVE existing ones to their reported state, and delete
OpenSky-owned aircraft not updated for 10 s.

Implementation uses stdlib ``urllib`` (the reference needs the
``requests`` package); in an offline environment the OPENSKY command
connects but every poll fails gracefully with an echo, exactly like
the reference when the network is down.
"""
import json
import time
import urllib.error
import urllib.request

import numpy as np

API_URL = "https://opensky-network.org/api"


def init_plugin(sim):
    reader = OpenSkyListener(sim)
    config = {
        "plugin_name": "OPENSKY",
        "plugin_type": "sim",
        "update_interval": 6.0,
        "preupdate": reader.update,
        "reset": reader.reset,
    }
    stackfunctions = {
        "OPENSKY": [
            "OPENSKY [on/off]",
            "[onoff]",
            reader.toggle,
            "Select OpenSky as a data source for traffic",
        ],
    }
    return config, stackfunctions


class OpenSkyListener:
    def __init__(self, sim):
        self.sim = sim
        self.connected = False
        self.my_ac = {}          # acid -> last update wall time
        self._warned = False

    def reset(self):
        self.connected = False
        self.my_ac = {}

    def toggle(self, flag=None):
        if flag is None:
            return True, ("OPENSKY is "
                          f"{'ON' if self.connected else 'OFF'}")
        if flag:
            self.connected = True
            self.sim.op()
            return True, "Connecting to OpenSky"
        self.connected = False
        return True, "Stopping the requests"

    def get_states(self):
        req = urllib.request.Request(API_URL + "/states/all")
        try:
            with urllib.request.urlopen(req, timeout=5) as r:
                payload = json.load(r)
        except (urllib.error.URLError, OSError, ValueError) as e:
            if not self._warned:
                self.sim.scr.echo(f"OPENSKY: request failed ({e}); "
                                  "retrying each interval")
                self._warned = True
            return None
        states = payload.get("states")
        return list(zip(*states)) if states else None

    def update(self):
        if not self.connected:
            return
        states = self.get_states()
        if states is None:
            return
        (icao24, acid, _orig, _tpos, _tcontact, lon, lat, _galt,
         _ongnd, spd, hdg, vspd, _sens, baro_alt, *_rest) = states[:14]

        def f(x):
            return np.array([v if v is not None else np.nan for v in x],
                            np.float64)

        lat, lon, alt = f(lat), f(lon), f(baro_alt)
        hdg, vspd, spd = f(hdg), f(vspd), f(spd)
        # null callsigns fall back to the icao24 hex id (str(None) is
        # truthy — guard on the raw value)
        acid = np.array([(i or "").strip() or str(h) for i, h in
                         zip(acid, icao24)])
        valid = ~np.logical_or.reduce(
            [np.isnan(x) for x in (lat, lon, alt, hdg, vspd, spd)])

        traf = self.sim.traf
        idx = np.array([traf.id2idx(a) for a in acid])
        newac = (idx < 0) & valid
        other = (idx >= 0) & valid
        curtime = time.time()

        n_new = int(newac.sum())
        if n_new:
            free = sum(1 for v in traf.ids if v is None)
            if n_new > free:     # keep within the padded capacity
                extra = np.flatnonzero(newac)[free:]
                newac[extra] = False
                n_new = free
        if n_new:
            traf.create(n_new, "B744", alt[newac], spd[newac], None,
                        lat[newac], lon[newac], hdg[newac],
                        list(acid[newac]))
            traf.flush()
            for a in acid[newac]:
                self.my_ac[a] = curtime
        if other.any():
            st = traf.state
            j = idx[other]
            put = lambda arr, val: arr.at[j].set(
                np.asarray(val, np.float64))
            ac = st.ac.replace(
                lat=put(st.ac.lat, lat[other]),
                lon=put(st.ac.lon, lon[other]),
                alt=put(st.ac.alt, alt[other]),
                hdg=put(st.ac.hdg, hdg[other]),
                trk=put(st.ac.trk, hdg[other]),
                selspd=put(st.ac.selspd, spd[other]),
                selvs=put(st.ac.selvs, vspd[other]))
            traf.state = st.replace(ac=ac)
            for a in acid[other]:
                if a in self.my_ac:
                    self.my_ac[a] = curtime
        # Drop OpenSky-owned aircraft silent for > 10 s
        dele = [a for a, t in self.my_ac.items()
                if curtime - t > 10.0 and traf.id2idx(a) >= 0]
        if dele:
            traf.delete([traf.id2idx(a) for a in dele])
            for a in dele:
                self.my_ac.pop(a, None)
