"""Sector occupancy count plugin.

Parity with the reference ``plugins/sectorcount.py``: registered named
areas are polled each interval; occupancy counts plus entered/left
callsign sets are echoed and logged to the OCCUPANCYLOG event logger.
"""
import numpy as np


def init_plugin(sim):
    sc = SectorCount(sim)
    config = {
        "plugin_name": "SECTORCOUNT",
        "plugin_type": "sim",
        "update_interval": 3.0,
        "update": sc.update,
        "reset": sc.reset,
    }
    stackfunctions = {
        "SECTORCOUNT": [
            "SECTORCOUNT LIST or ADD sectorname or REMOVE sectorname",
            "txt,[txt]",
            sc.command,
            "Add/remove/list sectors for occupancy count",
        ],
    }
    return config, stackfunctions


class SectorCount:
    def __init__(self, sim):
        self.sim = sim
        self.sectors = []
        self.previnside = []
        self.logger = sim.datalog.define_event(
            "OCCUPANCYLOG", "Sector count log: sector, count, "
            "entered, left")

    def reset(self):
        self.sectors = []
        self.previnside = []

    def command(self, sw, name=""):
        sw = sw.upper()
        if sw == "LIST":
            if not self.sectors:
                return True, "No sectors registered"
            return True, "Registered sectors: " + ", ".join(self.sectors)
        if sw == "ADD":
            if not self.sim.areas.hasArea(name.upper()):
                return False, f"Area {name} not found"
            if name.upper() in self.sectors:
                return True, f"Sector {name} already registered"
            self.sectors.append(name.upper())
            self.previnside.append(set())
            if not self.logger.active:
                self.logger.start(self.sim)
            return True, f"Added sector {name}"
        if sw == "REMOVE":
            if name.upper() not in self.sectors:
                return False, f"Sector {name} not registered"
            i = self.sectors.index(name.upper())
            self.sectors.pop(i)
            self.previnside.pop(i)
            return True, f"Removed sector {name}"
        return False, "SECTORCOUNT LIST/ADD/REMOVE"

    def update(self):
        if not self.sectors:
            return
        traf = self.sim.traf
        st = traf.state.ac
        lat = np.asarray(st.lat)
        lon = np.asarray(st.lon)
        alt = np.asarray(st.alt)
        active = np.asarray(st.active)
        for i, name in enumerate(self.sectors):
            inside = np.asarray(self.sim.areas.checkInside(
                name, lat, lon, alt)) & active
            ids = {traf.ids[k] for k in np.flatnonzero(inside)}
            arrived = ids - self.previnside[i]
            left = self.previnside[i] - ids
            self.previnside[i] = ids
            if arrived or left:
                self.logger.log(self.sim, [name], [len(ids)],
                                [",".join(sorted(arrived)) or "-"],
                                [",".join(sorted(left)) or "-"])
