"""Plugin system: discovery, loading, and per-plugin update scheduling.

Parity with the reference ``bluesky/tools/plugin.py:29-190``: plugin files
are recognised by AST scan for an ``init_plugin`` function (no import of
non-plugins), loaded on demand, and their ``preupdate`` / ``update`` /
``reset`` hooks run on per-plugin dt schedules; plugin stack commands are
appended to the command dictionary and removed on unload.  The
``PLUGINS LIST/LOAD/REMOVE`` stack command mirrors ``manage()``
(plugin.py:70-88).

TPU-first divergences:
* ``init_plugin(sim)`` receives the Simulation object — there are no
  module-global singletons in this framework, so plugins reach traffic /
  stack / areas through the sim handle (reference plugins do
  ``from bluesky import traf, sim``).  Plugins written for the reference
  need that one-line signature change.
* Hooks run at *chunk edges*: preupdate before the device chunk, update
  after it.  The Simulation clamps the chunk so edges land at least every
  ``min(plugin dt)`` of sim time — the hot scanned step never calls into
  Python.
* ``importlib`` instead of the removed ``imp`` module.
"""
import ast
import importlib.util
import os
import sys
from glob import glob

from .. import settings

# Built-in plugins shipped with the framework live next to this file.
BUILTIN_PATH = os.path.dirname(__file__)


class PluginDescription:
    def __init__(self, fname):
        self.fname = fname
        self.module_name = os.path.splitext(os.path.basename(fname))[0]
        self.plugin_doc = ""
        self.plugin_name = ""
        self.plugin_type = ""
        self.plugin_stack = []   # [(cmdname, helptext)]


def check_plugin(fname):
    """AST-scan a file for the init_plugin contract (plugin.py:29-67).

    Returns a PluginDescription or None.  Never imports the module; the
    config dict's plugin_name/plugin_type string constants are read from
    the parse tree.
    """
    try:
        with open(fname, "rb") as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        return None
    for item in tree.body:
        if not (isinstance(item, ast.FunctionDef)
                and item.name == "init_plugin"):
            continue
        plugin = PluginDescription(fname)
        plugin.plugin_doc = ast.get_docstring(tree) or ""
        ret_dicts = []
        ret_names = ["", ""]
        for iitem in reversed(item.body):
            if isinstance(iitem, ast.Return):
                if not (isinstance(iitem.value, (ast.Tuple, ast.List))
                        and len(iitem.value.elts) == 2):
                    return None
                ret_dicts = list(iitem.value.elts)
                ret_names = [el.id if isinstance(el, ast.Name) else ""
                             for el in ret_dicts]
            if isinstance(iitem, ast.Assign) \
                    and isinstance(iitem.value, ast.Dict) \
                    and isinstance(iitem.targets[0], ast.Name):
                for i in range(2):
                    if iitem.targets[0].id == ret_names[i]:
                        ret_dicts[i] = iitem.value
        if len(ret_dicts) != 2 or not all(
                isinstance(d, ast.Dict) for d in ret_dicts):
            return None
        cfg = {k.value: v for k, v in zip(ret_dicts[0].keys,
                                          ret_dicts[0].values)
               if isinstance(k, ast.Constant)}
        name = cfg.get("plugin_name")
        ptype = cfg.get("plugin_type")
        if not (isinstance(name, ast.Constant)
                and isinstance(ptype, ast.Constant)):
            return None
        plugin.plugin_name = str(name.value)
        plugin.plugin_type = str(ptype.value)
        for k, v in zip(ret_dicts[1].keys, ret_dicts[1].values):
            if isinstance(k, ast.Constant):
                doc = ""
                if isinstance(v, (ast.List, ast.Tuple)) and v.elts \
                        and isinstance(v.elts[-1], ast.Constant):
                    doc = str(v.elts[-1].value)
                plugin.plugin_stack.append((str(k.value).upper(), doc))
        return plugin
    return None


class PluginManager:
    """Per-Simulation plugin registry + hook scheduler."""

    def __init__(self, sim, mode="sim"):
        self.sim = sim
        self.mode = mode
        self.descriptions = {}
        self.active = {}
        # name -> [next_trigger_t, dt, fun]
        self.preupdate_funs = {}
        self.update_funs = {}
        self.reset_funs = {}
        self.discover()

    # ----------------------------------------------------------- discovery
    def discover(self):
        """Scan the builtin package dir + settings.plugin_path
        (plugin.py:91-105)."""
        dirs = [BUILTIN_PATH]
        ext = os.path.abspath(settings.plugin_path)
        if os.path.isdir(ext) and ext != BUILTIN_PATH:
            dirs.append(ext)
        for d in dirs:
            for fname in sorted(glob(os.path.join(d, "*.py"))):
                if os.path.basename(fname) == "__init__.py":
                    continue
                p = check_plugin(fname)
                if p and p.plugin_type == self.mode:
                    self.descriptions[p.plugin_name.upper()] = p

    # ------------------------------------------------------------- manage
    def manage(self, cmd="LIST", name=""):
        """PLUGINS LIST/LOAD/REMOVE (plugin.py:70-88)."""
        cmd = (cmd or "LIST").upper()
        name = (name or "").upper()
        if cmd == "LIST":
            running = sorted(self.active)
            avail = sorted(set(self.descriptions) - set(self.active))
            text = "Currently running plugins: " + (", ".join(running)
                                                    or "-")
            text += ("\nAvailable plugins: " + ", ".join(avail)) if avail \
                else "\nNo additional plugins available."
            return True, text
        if cmd in ("LOAD", "ENABLE"):
            return self.load(name)
        if cmd in ("REMOVE", "UNLOAD", "DISABLE"):
            return self.remove(name)
        # bare name given -> load it
        return self.load(cmd)

    def load(self, name):
        if name in self.active:
            return False, f"Plugin {name} already loaded"
        descr = self.descriptions.get(name)
        if not descr:
            return False, f"Error loading plugin: plugin {name} not found."
        # Snapshot traffic hook lists so unload can strip what the plugin's
        # init adds (reference plugins attach via TrafficArrays parenting;
        # here via traf.create_hooks/delete_hooks).
        traf = self.sim.traf
        n_create_hooks = len(traf.create_hooks)
        n_delete_hooks = len(traf.delete_hooks)
        try:
            if os.path.dirname(os.path.abspath(descr.fname)) \
                    == BUILTIN_PATH:
                # Shipped plugins are real package submodules (they use
                # relative imports into the framework)
                mod = importlib.import_module(
                    f"{__name__}.{descr.module_name}")
            else:
                # External plugins load from file; they must use absolute
                # imports (``import bluesky_tpu...``)
                spec = importlib.util.spec_from_file_location(
                    f"bluesky_tpu_plugin_{descr.module_name}", descr.fname)
                mod = importlib.util.module_from_spec(spec)
                sys.modules[spec.name] = mod
                try:
                    spec.loader.exec_module(mod)
                except Exception:
                    sys.modules.pop(spec.name, None)
                    raise
            config, stackfuns = mod.init_plugin(self.sim)
        except Exception as e:
            # Strip any traffic hooks a half-initialized plugin attached
            del traf.create_hooks[n_create_hooks:]
            del traf.delete_hooks[n_delete_hooks:]
            return False, f"Failed to load {name}: {e}"
        self.active[name] = mod
        self._hooks = getattr(self, "_hooks", {})
        self._hooks[name] = (traf.create_hooks[n_create_hooks:],
                             traf.delete_hooks[n_delete_hooks:])
        dt = max(float(config.get("update_interval", 0.0)), self.sim.simdt)
        simt = self.sim.simt
        if config.get("preupdate"):
            self.preupdate_funs[name] = [simt + dt, dt,
                                         config["preupdate"]]
        if config.get("update"):
            self.update_funs[name] = [simt + dt, dt, config["update"]]
        if config.get("reset"):
            self.reset_funs[name] = config["reset"]
        self.sim.stack.append_commands(stackfuns)
        descr.plugin_stack = [(k.upper(), v[-1]) for k, v in
                              stackfuns.items()]
        # Loggers the plugin created get their auto stack command
        # (FLSTLOG ON/OFF...; datalog.py:106-110 contract)
        self.sim.datalog.register_stack_commands(self.sim)
        return True, f"Successfully loaded plugin {name}"

    def remove(self, name):
        if name not in self.active:
            return False, f"Plugin {name} not loaded"
        rst = self.reset_funs.pop(name, None)
        if rst:
            # Reference parity: remove() calls the plugin reset first "to
            # clear plugin state just in case" (plugin.py:147-151).
            rst()
        descr = self.descriptions[name]
        self.sim.stack.remove_commands([c for c, _ in descr.plugin_stack])
        self.active.pop(name)
        self.preupdate_funs.pop(name, None)
        self.update_funs.pop(name, None)
        # Strip the traffic hooks this plugin's init registered
        chooks, dhooks = getattr(self, "_hooks", {}).pop(name, ([], []))
        traf = self.sim.traf
        traf.create_hooks = [h for h in traf.create_hooks
                             if h not in chooks]
        traf.delete_hooks = [h for h in traf.delete_hooks
                             if h not in dhooks]
        return True, f"Removed plugin {name}"

    # ---------------------------------------------------------- scheduling
    def min_dt(self):
        """Smallest hook interval of the active plugins (None if none):
        the Simulation clamps the device chunk to this."""
        dts = [f[1] for f in self.preupdate_funs.values()]
        dts += [f[1] for f in self.update_funs.values()]
        return min(dts) if dts else None

    def has_due(self, simt):
        """Any preupdate/update hook due at (or before) ``simt``?  The
        pipelined chunk loop asks this BEFORE dispatching: a due hook
        may read or mutate state, so its edge must run synchronously.
        Same epsilon as ``_run_due``."""
        return any(simt >= fun[0] - 1e-9
                   for funs in (self.preupdate_funs, self.update_funs)
                   for fun in funs.values())

    def _run_due(self, funs, simt):
        for fun in funs.values():
            if simt >= fun[0] - 1e-9:
                fun[0] += fun[1]
                # Catch up if more than one interval passed in a chunk
                if simt >= fun[0] - 1e-9:
                    fun[0] = simt + fun[1]
                fun[2]()

    def preupdate(self, simt):
        self._run_due(self.preupdate_funs, simt)

    def update(self, simt):
        self._run_due(self.update_funs, simt)

    def reset(self):
        """Reset trigger times + call plugin reset hooks (plugin.py:177-190)."""
        for fun in self.preupdate_funs.values():
            fun[0] = fun[1]
        for fun in self.update_funs.values():
            fun[0] = fun[1]
        for fun in self.reset_funs.values():
            fun()
