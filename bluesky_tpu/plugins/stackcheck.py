"""Stack fuzz-tester: iterate every registered command inside a running
sim.

Parity with the reference ``plugins/stackcheck.py:15-418`` (a runtime
harness that walks the command dictionary and fires each command with
plausible arguments, watching for crashes).  Redesigned generically:
instead of the reference's hand-written per-command test list, arguments
are synthesized from each command's argtype spec, so new commands are
fuzzed automatically.  STACKCHECK runs the whole sweep in one call and
echoes a summary; commands that would end the run (QUIT/RESET/IC/...)
are skipped like the reference's exclude list.
"""

SKIP = {
    "QUIT", "RESET", "IC", "BATCH", "ADDNODES", "SAVEIC", "SCEN",
    "PCALL", "BENCHMARK", "STACKCHECK", "MAKEDOC", "SNAPSHOT",
    "PROFILE", "CD", "HOLD", "OP", "FF", "DELALL", "PLUGINS",
    # filesystem side effects (snapshots/logs/renders)
    "SCREENSHOT", "DUMPRTE", "SNAPLOG", "INSTLOG", "SKYLOG",
    "FLSTLOG", "OCCUPANCYLOG", "METLOG",
}

SAMPLE_ARGS = {
    "acid": "FUZZ1", "txt": "FUZZ1", "word": "fuzz", "string": "ECHO hi",
    "float": "1.5", "int": "2", "onoff": "ON", "alt": "FL100",
    "spd": "250", "vspd": "1000", "hdg": "90", "time": "60",
    "lat": "52.0", "lon": "4.0", "latlon": "52.0 4.0", "wpt": "52.0 4.0",
    "wppos": "52.0 4.0",
    "wpinroute": "WP001", "pandir": "LEFT", "color": "RED",
}


def init_plugin(sim):
    sc = StackCheck(sim)
    config = {
        "plugin_name": "STACKCHECK",
        "plugin_type": "sim",
        "update_interval": 0.0,
    }
    stackfunctions = {
        "STACKCHECK": [
            "STACKCHECK [command]",
            "[txt]",
            sc.run,
            "Fuzz every registered stack command (or one) with "
            "synthesized arguments",
        ],
    }
    return config, stackfunctions


class StackCheck:
    def __init__(self, sim):
        self.sim = sim
        self._running = False

    def _args_for(self, argtypes):
        out = []
        for tok in (argtypes or "").split(","):
            t = tok.strip().strip("[]").strip()
            if not t or t == "...":
                continue
            base = t.split("/")[0]
            out.append(SAMPLE_ARGS.get(base, "1"))
        return out

    def run(self, which=None):
        if self._running:       # re-entry guard (defense in depth)
            return True, "STACKCHECK already running"
        self._running = True
        try:
            return self._run(which)
        finally:
            self._running = False

    def _run(self, which):
        sim = self.sim
        stack = sim.stack
        # A test subject for acid-taking commands
        if sim.traf.id2idx("FUZZ1") < 0:
            sim.traf.create(1, "B744", 6000.0, 120.0, None, 52.0, 4.0,
                            90.0, "FUZZ1")
            sim.traf.flush()
            sim.routes.addwpt(sim.traf.id2idx("FUZZ1"), "WP001",
                              52.0, 5.0)
        names = [which.upper()] if which else sorted(stack.cmddict)
        failed = []
        tested = 0
        for name in names:
            if name in SKIP or name not in stack.cmddict:
                continue
            usage, argtypes, fn, _help = stack.cmddict[name]
            line = " ".join([name] + self._args_for(argtypes))
            # Capture this command's echoes via a tee — echobuf indices
            # are unreliable (ScreenIO bounds the buffer)
            collected = []
            orig_echo = sim.scr.echo

            def tee(text="", flags=0, _c=collected, _o=orig_echo):
                _c.append(text)
                return _o(text, flags)

            sim.scr.echo = tee
            try:
                stack.stack(line)
                stack.process()
            except Exception as e:  # noqa: BLE001 — fuzzing for crashes
                failed.append(f"{name}: {type(e).__name__}: {e}")
                continue
            finally:
                sim.scr.echo = orig_echo
            out = "\n".join(collected)
            if "failed:" in out:
                failed.append(f"{name}: {out.splitlines()[0]}")
            tested += 1
        msg = f"STACKCHECK: {tested} commands fired, {len(failed)} failed"
        if failed:
            msg += "\n" + "\n".join(failed[:20])
        return len(failed) == 0, msg
