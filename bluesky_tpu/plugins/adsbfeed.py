"""Mode-S/ADS-B live feed plugin (Beast/AVR TCP stream + pyModeS).

Parity with the reference ``plugins/adsbfeed.py`` + ``adsb_decoder.py``:
connect a raw TCP stream of Mode-S frames (dump1090-style), decode
identification/position/velocity messages, and drive the traffic
arrays from the decoded reports.

The decoder depends on the optional ``pyModeS`` package (same as the
reference); the transport and framing run on stdlib sockets.  Without
pyModeS the plugin loads but ADSBFEED reports the missing dependency —
mirroring the reference's optional-dependency behavior (e.g. SSD and
pyclipper).
"""
import socket
import threading
import time

try:
    import pyModeS as pms
except ImportError:          # optional, like the reference
    pms = None


def init_plugin(sim):
    feed = AdsbFeed(sim)
    config = {
        "plugin_name": "ADSBFEED",
        "plugin_type": "sim",
        "update_interval": 1.0,
        "preupdate": feed.update,
        "reset": feed.reset,
    }
    stackfunctions = {
        "ADSBFEED": [
            "ADSBFEED [ON/OFF or host[:port]]",
            "[txt]",
            feed.toggle,
            "Receive live Mode-S/ADS-B traffic from a raw TCP feed",
        ],
    }
    return config, stackfunctions


class AdsbFeed:
    def __init__(self, sim):
        self.sim = sim
        self.host = "127.0.0.1"
        self.port = 30002        # dump1090 raw output
        self.running = False
        self._thread = None
        self._lock = threading.Lock()
        self._frames = []        # raw hex frames from the reader thread
        self.acpos = {}          # icao -> dict(lat, lon, alt, spd, hdg,
        #                                        vs, callsign, t)

    # ------------------------------------------------------------ control
    def toggle(self, arg=None):
        if pms is None:
            return False, ("ADSBFEED needs the optional pyModeS package "
                           "(not installed) — same dependency as the "
                           "reference plugin")
        if arg is None:
            return True, f"ADSBFEED is {'ON' if self.running else 'OFF'}"
        a = str(arg).upper()
        if a in ("OFF", "FALSE", "0"):
            self.running = False
            return True, "ADSBFEED stopped"
        if a not in ("ON", "TRUE", "1"):
            host = str(arg)
            if ":" in host:
                host, port = host.rsplit(":", 1)
                self.port = int(port)
            self.host = host
        # Stop any existing reader before (re)connecting so a repeat ON
        # or a host switch never leaves two connections streaming
        if self._thread is not None and self._thread.is_alive():
            self.running = False
            self._thread.join(timeout=3)
        self.running = True
        self._thread = threading.Thread(target=self._reader, daemon=True)
        self._thread.start()
        return True, f"ADSBFEED connecting to {self.host}:{self.port}"

    def reset(self):
        self.running = False
        self.acpos = {}

    # ------------------------------------------------------- reader thread
    def _reader(self):
        try:
            conn = socket.create_connection((self.host, self.port),
                                            timeout=5)
        except OSError as e:
            self.sim.scr.echo(f"ADSBFEED: connect failed: {e}")
            self.running = False
            return
        conn.settimeout(1.0)
        buf = b""
        while self.running:
            try:
                data = conn.recv(4096)
            except socket.timeout:
                continue
            except OSError:
                break
            if not data:
                break
            buf += data
            # dump1090 raw format: '*<hex>;\n'
            while b";" in buf:
                frame, buf = buf.split(b";", 1)
                frame = frame.strip().lstrip(b"*")
                if frame:
                    with self._lock:
                        self._frames.append(frame.decode("ascii",
                                                         "ignore"))
        conn.close()

    # ------------------------------------------------------------- update
    def update(self):
        """Decode buffered frames and sync the traffic arrays
        (adsb_decoder.py semantics: DF17 ident/position/velocity)."""
        if pms is None or not self.running:
            return
        with self._lock:
            frames, self._frames = self._frames, []
        now = time.time()
        for msg in frames:
            if len(msg) != 28 or pms.df(msg) != 17:
                continue
            icao = pms.adsb.icao(msg)
            tc = pms.adsb.typecode(msg)
            rec = self.acpos.setdefault(icao, {"t": now})
            rec["t"] = now
            if 1 <= tc <= 4:
                rec["callsign"] = pms.adsb.callsign(msg).strip("_")
            elif 9 <= tc <= 18:
                pos = pms.adsb.position_with_ref(
                    msg, rec.get("lat", 52.0), rec.get("lon", 4.0))
                if pos:
                    rec["lat"], rec["lon"] = pos
                rec["alt"] = (pms.adsb.altitude(msg) or 0) * 0.3048
            elif tc == 19:
                vel = pms.adsb.velocity(msg)
                if vel:
                    spd, hdg, vs, _ = vel
                    rec["spd"] = (spd or 0) * 0.514444
                    rec["hdg"] = hdg or 0.0
                    rec["vs"] = (vs or 0) * 0.00508
        self._sync(now)

    def _sync(self, now):
        traf = self.sim.traf
        stale = [k for k, r in self.acpos.items() if now - r["t"] > 30.0]
        for k in stale:
            r = self.acpos.pop(k)
            i = traf.id2idx(r.get("acid_used", ""))
            if isinstance(i, int) and i >= 0:
                traf.delete(i)
        for icao, r in self.acpos.items():
            if "lat" not in r or "spd" not in r:
                continue        # need a full state before creating
            acid = (r.get("callsign") or icao).upper()
            used = r.get("acid_used")
            if used is not None and used != acid:
                # ident frame arrived after creation under the hex icao:
                # retire the old slot so the airframe never duplicates
                old = traf.id2idx(used)
                if isinstance(old, int) and old >= 0:
                    traf.delete(old)
                r.pop("acid_used")
            i = traf.id2idx(acid)
            if not isinstance(i, int) or i < 0:
                if not any(v is None for v in traf.ids):
                    continue    # capacity full
                traf.create(1, "B744", r.get("alt", 0.0), r["spd"],
                            None, r["lat"], r["lon"], r.get("hdg", 0.0),
                            acid)
                traf.flush()
                r["acid_used"] = acid
            else:
                st = traf.state
                ac = st.ac
                put = lambda a, v: a.at[i].set(float(v))
                traf.state = st.replace(ac=ac.replace(
                    lat=put(ac.lat, r["lat"]), lon=put(ac.lon, r["lon"]),
                    alt=put(ac.alt, r.get("alt", 0.0)),
                    hdg=put(ac.hdg, r.get("hdg", 0.0)),
                    trk=put(ac.trk, r.get("hdg", 0.0)),
                    selspd=put(ac.selspd, r["spd"]),
                    selvs=put(ac.selvs, r.get("vs", 0.0))))
