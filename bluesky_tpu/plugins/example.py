"""Example plugin: the template for writing bluesky_tpu plugins.

Mirrors the reference ``plugins/example.py`` contract, adapted to this
framework's one difference: ``init_plugin(sim)`` receives the
Simulation handle (there are no global singletons) — reach traffic as
``sim.traf``, the stack as ``sim.stack``, areas as ``sim.areas``.
"""


def init_plugin(sim):
    ex = Example(sim)
    config = {
        # The name of your plugin
        "plugin_name": "EXAMPLE",
        # Only simulation plugins exist for now
        "plugin_type": "sim",
        # Update interval in seconds (hooks run at chunk edges)
        "update_interval": 1.0,
        # update() is called after the traffic step
        "update": ex.update,
        # preupdate() is called before the traffic step
        "preupdate": ex.preupdate,
        # reset() is called on simulation reset
        "reset": ex.reset,
    }
    stackfunctions = {
        "MYFUN": [
            "MYFUN ON/OFF",
            "[onoff]",
            ex.myfun,
            "Example plugin command: echo the flag you pass",
        ],
    }
    return config, stackfunctions


class Example:
    def __init__(self, sim):
        self.sim = sim
        self.n_updates = 0

    def update(self):
        self.n_updates += 1

    def preupdate(self):
        pass

    def reset(self):
        self.n_updates = 0

    def myfun(self, flag=True):
        return True, (f"MYFUN is {'ON' if flag else 'OFF'}; "
                      f"{self.n_updates} updates so far, "
                      f"{self.sim.traf.ntraf} aircraft flying")
