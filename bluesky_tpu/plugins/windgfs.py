"""Wind field from NOAA GFS forecasts.

Parity with the reference ``plugins/windgfs.py``: download the GFS
0.25-degree grib slice for the simulated UTC time and area, extract the
u/v wind profiles, and load them into the simulation wind field.

The grib decode depends on the optional ``pygrib`` package, exactly
like the reference; the download uses stdlib urllib.  Without pygrib
(or network) the WINDGFS command reports the missing dependency and
the plugin stays loadable — the reference behaves the same when its
optional deps are absent.
"""
import os
import urllib.error
import urllib.request

import numpy as np

try:
    import pygrib
except ImportError:          # optional, like the reference
    pygrib = None

NOMADS_URL = ("https://nomads.ncep.noaa.gov/cgi-bin/"
              "filter_gfs_0p25.pl")


def init_plugin(sim):
    wgfs = WindGFS(sim)
    config = {
        "plugin_name": "WINDGFS",
        "plugin_type": "sim",
        "update_interval": 3600.0,
        "update": wgfs.update,
        "reset": wgfs.reset,
    }
    stackfunctions = {
        "WINDGFS": [
            "WINDGFS [lat0,lon0,lat1,lon1]",
            "[lat,lon,lat,lon]",
            wgfs.fetch,
            "Load a GFS wind field for the given area at the "
            "simulated time",
        ],
    }
    return config, stackfunctions


class WindGFS:
    def __init__(self, sim):
        self.sim = sim
        self.area = (48.0, -6.0, 56.0, 12.0)
        self.active = False

    def reset(self):
        self.active = False

    def fetch(self, lat0=None, lon0=None, lat1=None, lon1=None):
        """WINDGFS [area]: download + decode + install the wind field."""
        if pygrib is None:
            return False, ("WINDGFS needs the optional pygrib package "
                           "(not installed) — same dependency as the "
                           "reference plugin")
        if lat0 is not None:
            self.area = (lat0, lon0, lat1, lon1)
        utc = self.sim.utc
        ymd = utc.strftime("%Y%m%d")
        hour = (utc.hour // 6) * 6
        lat0, lon0, lat1, lon1 = self.area
        params = (f"?file=gfs.t{hour:02d}z.pgrb2.0p25.f000"
                  f"&lev_250_mb=on&lev_500_mb=on&lev_700_mb=on"
                  f"&lev_850_mb=on&var_UGRD=on&var_VGRD=on"
                  f"&subregion=&leftlon={lon0}&rightlon={lon1}"
                  f"&toplat={lat1}&bottomlat={lat0}"
                  f"&dir=%2Fgfs.{ymd}%2F{hour:02d}%2Fatmos")
        try:
            with urllib.request.urlopen(NOMADS_URL + params,
                                        timeout=30) as r:
                data = r.read()
        except (urllib.error.URLError, OSError) as e:
            return False, f"WINDGFS: download failed ({e})"
        from bluesky_tpu import settings
        tmp = os.path.join(settings.log_path, "gfs_wind.grb2")
        os.makedirs(settings.log_path, exist_ok=True)
        with open(tmp, "wb") as f:
            f.write(data)
        return self._install(tmp)

    # Pressure level -> approximate ISA altitude [m]
    LEVELS = {850: 1457.0, 700: 3012.0, 500: 5574.0, 250: 10363.0}

    def _install(self, fname):
        grbs = pygrib.open(fname)
        u = {}
        v = {}
        lats = lons = None
        for grb in grbs:
            lev = grb.level
            if grb.shortName == "u":
                u[lev] = grb.values
            elif grb.shortName == "v":
                v[lev] = grb.values
            if lats is None:
                lats, lons = grb.latlons()
        grbs.close()
        if not u or lats is None:
            return False, "WINDGFS: no wind records in the grib file"
        # Subsample the grid into wind field points with altitude
        # profiles (core/wind.py add_point API)
        from ..core import wind as windmod
        st = self.sim.traf.state
        wind = st.wind
        step = max(1, lats.shape[0] // 4), max(1, lats.shape[1] // 4)
        npts = 0
        for i in range(0, lats.shape[0], step[0]):
            for j in range(0, lats.shape[1], step[1]):
                alts, dirs, spds = [], [], []
                for lev, alt in sorted(self.LEVELS.items(),
                                       key=lambda kv: kv[1]):
                    if lev not in u:
                        continue
                    uu, vv = u[lev][i, j], v[lev][i, j]
                    spd = float(np.hypot(uu, vv))
                    wdir = float((np.degrees(np.arctan2(uu, vv))
                                  + 180.0) % 360.0)
                    alts.append(alt)
                    dirs.append(wdir)
                    spds.append(spd)
                if alts:
                    wind = windmod.add_point(
                        wind, float(lats[i, j]), float(lons[i, j]),
                        dirs, spds, windalt=alts)
                    npts += 1
        self.sim.traf.state = st.replace(wind=wind)
        self.active = True
        return True, f"WINDGFS: wind field loaded ({npts} points)"

    def update(self):
        pass        # refresh handled by re-issuing WINDGFS
