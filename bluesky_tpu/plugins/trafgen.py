"""Traffic generator plugin: sources/drains spawning flows of aircraft.

Parity with the reference ``plugins/trafgen.py`` + ``trafgenclasses.py``
(the Airspace Design Contest generator, and the named driver of the
10k/50k/100k density-sweep benchmark config — BASELINE.md config #3):
a spawn circle with 12 ``SEGM<brg>`` edge segments, named Source and
Drain objects (airports / waypoints / segments), per-object flow rates in
aircraft/hour, runway takeoff queues with a minimum takeoff interval,
aircraft-type pools, altitude/speed start windows, and random
destination/origin selection per spawn.

TPU-first divergences:
* Spawns are *batched*: each update draws the number of spawns per
  source from the exact Poisson law for ``gain*flow*dt`` (the reference
  Bernoulli-per-0.1 s tick caps every source at 10 a/c s^-1 and distorts
  high flows; Poisson is the limit the reference approximates) and issues
  ONE ``traf.create`` call for the whole batch, landing on device as one
  write.  High-density sweeps spin up in sim-minutes instead of hours.
* Follow-up guidance (DEST/ORIG/LNAV) is issued through the same stack
  command strings the reference emits — the stack remains the universal
  API surface.
* All state hangs off the plugin instance (one per Simulation), not
  module globals.
"""
import numpy as np

NM = 1852.0


def init_plugin(sim):
    gen = TrafGen(sim)
    config = {
        "plugin_name": "TRAFGEN",
        "plugin_type": "sim",
        "update_interval": 0.1,
        "update": gen.update,
        "reset": gen.reset,
    }
    stackfunctions = {
        "TRAFGEN": [
            "TRAFGEN [location],cmd,[arg,arg,...]",
            "string",
            gen.command,
            "Traffic-generator (contest) command",
        ],
    }
    return config, stackfunctions


class Flowpoint:
    """Shared geometry/config of a Source or Drain endpoint."""

    def __init__(self, gen, name):
        self.gen = gen
        self.name = name.upper()
        self.flow = 0.0                  # [a/c per hour]
        self.actypes = ["B744"]
        self.startaltmin = None          # [ft]
        self.startaltmax = None
        self.startspdmin = None          # [kts CAS]
        self.startspdmax = None
        self.seg = self.name.startswith("SEGM")
        if self.seg:
            brg = float(self.name[4:])
            self.lat, self.lon = gen.segpos(brg)
            self.hdg = (brg + 180.0) % 360.0   # inward
            self.incircle = False
        else:
            pos = gen.resolve(self.name)
            if pos is None:
                raise ValueError(f"{name}: position not found")
            self.lat, self.lon = pos
            self.hdg = None
            self.incircle = gen.incircle(self.lat, self.lon)
            if not self.incircle:
                # Project to the circle edge segment toward the point
                # (trafgenclasses.py:58-64)
                brg = _bearing(gen.ctrlat, gen.ctrlon, self.lat, self.lon)
                self.lat, self.lon = gen.segpos(brg)
                self.hdg = (brg + 180.0) % 360.0
                self.seg = True
        # Runway queues (sources only)
        self.runways = []                # [(name, lat, lon, hdg)]
        self.rwyline = []                # queued takeoffs
        self.rwytotime = []              # last takeoff time
        self.dtakeoff = 90.0

    def setflow(self, val):
        self.flow = float(val)
        return True

    def addactypes(self, types):
        self.actypes = [t.upper() for t in types] or self.actypes
        return True

    def setalt(self, args):
        vals = [float(a.lstrip("FL")) * (100.0 if a.startswith("FL") else 1.0)
                for a in args]
        self.startaltmin = vals[0]
        self.startaltmax = vals[-1]
        return True

    def setspd(self, args):
        vals = [float(a) for a in args]
        self.startspdmin = vals[0]
        self.startspdmax = vals[-1]
        return True

    def sethdg(self, args):
        self.hdg = float(args[0]) % 360.0
        return True

    def setrunways(self, names):
        self.runways = []
        self.rwyline = []
        self.rwytotime = []
        navdb = self.gen.sim.navdb
        thresholds = getattr(navdb, "rwythresholds", {})
        for rwy in names:
            r = rwy.upper().removeprefix("RWY").removeprefix("RW")
            thr = thresholds.get(self.name, {}).get(r)
            if thr is not None:
                rlat, rlon, rhdg = thr[0], thr[1], thr[2]
            else:
                rlat, rlon = self.lat, self.lon
                try:
                    rhdg = 10.0 * float("".join(
                        c for c in r if c.isdigit()))
                except ValueError:
                    rhdg = 0.0
            self.runways.append((rwy.upper(), rlat, rlon, rhdg))
            self.rwyline.append(0)
            self.rwytotime.append(-999.0)
        return True

    def start_alt_spd(self, rng, n):
        """Per-spawn altitude [ft] / speed [kts] draws
        (trafgenclasses.py:358-364 defaults)."""
        if self.startaltmin is not None:
            alt = rng.uniform(self.startaltmin, self.startaltmax, n)
        else:
            alt = rng.integers(200, 301, n) * 100.0
        if self.startspdmin is not None:
            spd = rng.uniform(self.startspdmin, self.startspdmax, n)
        else:
            spd = rng.integers(250, 351, n).astype(float)
        return alt, spd


class Source(Flowpoint):
    def __init__(self, gen, name):
        super().__init__(gen, name)
        self.dest = []                   # [(name_or_None, lat, lon)]

    def adddest(self, args):
        for d in args:
            d = d.upper()
            if d.startswith("SEGM"):
                lat, lon = self.gen.segpos(float(d[4:]))
                self.dest.append((d, lat, lon))
            else:
                pos = self.gen.resolve(d)
                if pos is None:
                    return False
                self.dest.append((d, pos[0], pos[1]))
        return True


class Drain(Flowpoint):
    def __init__(self, gen, name):
        super().__init__(gen, name)
        self.orig = []                   # [(name, lat, lon, incircle)]

    def addorig(self, args):
        for o in args:
            o = o.upper()
            if o.startswith("SEGM"):
                lat, lon = self.gen.segpos(float(o[4:]))
                self.orig.append((o, lat, lon, False))
            else:
                pos = self.gen.resolve(o)
                if pos is None:
                    return False
                self.orig.append((o, pos[0], pos[1],
                                  self.gen.incircle(pos[0], pos[1])))
        return True


class TrafGen:
    def __init__(self, sim):
        self.sim = sim
        self.rng = np.random.default_rng(12345)
        self.reset()

    def reset(self):
        self.ctrlat = 52.6
        self.ctrlon = 5.4
        self.radius = 230.0              # [nm]
        self.gain = 1.0
        self.sources = {}
        self.drains = {}
        self.last_t = float(self.sim.simt)
        self._fltnr = 100
        # Draw the spawn circle like the reference reset() does
        self.sim.stack.stack(
            f"CIRCLE SPAWN,{self.ctrlat},{self.ctrlon},{self.radius}")

    # ----------------------------------------------------------- geometry
    def segpos(self, brg):
        """Position on the spawn circle at bearing brg from the centre."""
        from ..ops.geo import kwikpos
        lat, lon = kwikpos(self.ctrlat, self.ctrlon, brg % 360.0,
                           self.radius)   # dist in [nm]
        return float(lat), float(lon)

    def incircle(self, lat, lon):
        from ..ops.geo import kwikdist_wrapped
        return float(kwikdist_wrapped(self.ctrlat, self.ctrlon, lat, lon,
                                      xp=np)) <= self.radius

    def resolve(self, name):
        """Named position via the navdb (airport first)."""
        try:
            return self.sim.navdb.txt2pos(name, self.ctrlat, self.ctrlon)
        except Exception:
            return None

    # ------------------------------------------------------------ command
    def command(self, cmdline=""):
        """TRAFGEN subcommand dispatch (trafgen.py:107-246)."""
        words = [w for w in cmdline.replace(",", " ").split() if w]
        if not words:
            return True, ("TRAFGEN CIRCLE/GAIN/SRC/DRN ... | sources: "
                          + ", ".join(self.sources)
                          + " | drains: " + ", ".join(self.drains))
        cmd = words[0].upper()
        args = words[1:]
        try:
            if cmd in ("CIRCLE", "CIRC"):
                self.ctrlat, self.ctrlon = float(args[0]), float(args[1])
                self.radius = float(args[2])
                self.sim.stack.stack("DEL SPAWN")
                self.sim.stack.stack(
                    f"CIRCLE SPAWN,{self.ctrlat},{self.ctrlon},"
                    f"{self.radius}")
                return True
            if cmd in ("GAIN", "FACTOR"):
                self.gain = float(args[0])
                return True
            if cmd in ("SRC", "SOURCE"):
                return self._object_cmd(self.sources, Source, args)
            if cmd in ("DRN", "DRAIN"):
                return self._object_cmd(self.drains, Drain, args)
        except (IndexError, ValueError) as e:
            return False, f"TRAFGEN {cmd}: bad arguments ({e})"
        return False, f"TRAFGEN: unknown subcommand {cmd}"

    def _object_cmd(self, table, cls, args):
        name = args[0].upper()
        sub = args[1].upper() if len(args) > 1 else ""
        subargs = args[2:]
        if name not in table:
            try:
                table[name] = cls(self, name)
            except ValueError as e:
                return False, f"TRAFGEN ERROR {e}"
        obj = table[name]
        ok = True
        if sub in ("RUNWAY", "RWY", "RUNWAYS"):
            ok = obj.setrunways(subargs)
        elif sub == "DEST":
            ok = obj.adddest(subargs)
        elif sub == "ORIG":
            ok = obj.addorig(subargs)
        elif sub == "FLOW":
            ok = obj.setflow(subargs[0])
        elif sub in ("TYPES", "TYPE"):
            ok = obj.addactypes(subargs)
        elif sub == "ALT":
            ok = obj.setalt(subargs)
        elif sub == "SPD":
            ok = obj.setspd(subargs)
        elif sub == "HDG":
            ok = obj.sethdg(subargs)
        elif sub:
            return False, f"TRAFGEN {name}: unknown subcommand {sub}"
        if not ok:
            return False, f"TRAFGEN {name} {sub}: error"
        return True

    # ------------------------------------------------------------- update
    def update(self):
        t = self.sim.simt
        dt = max(0.0, t - self.last_t)
        self.last_t = t
        if dt <= 0.0:
            return
        for src in self.sources.values():
            self._update_source(src, dt, t)
        for drn in self.drains.values():
            self._update_drain(drn, dt, t)

    def _spawn_count(self, obj, dt):
        lam = self.gain * obj.flow * dt / 3600.0
        return int(self.rng.poisson(lam)) if lam > 0.0 else 0

    def _acid(self, prefix):
        # Skip callsigns already flying (a fresh TrafGen after PLUGINS
        # REMOVE/LOAD restarts its counter while aircraft persist)
        while True:
            self._fltnr += 1
            acid = f"{prefix[:3]}{self._fltnr:04d}"
            if self.sim.traf.id2idx(acid) < 0:
                return acid

    def _update_source(self, src, dt, t):
        """Spawn from a source: runway queues or instant at position
        (trafgenclasses.py:252-396, batched)."""
        n_new = self._spawn_count(src, dt)
        stack = self.sim.stack
        if src.runways:
            # Queue arrivals on random runways, release per dtakeoff
            for _ in range(n_new):
                src.rwyline[self.rng.integers(len(src.runways))] += 1
            for i, (rwy, rlat, rlon, rhdg) in enumerate(src.runways):
                if src.rwyline[i] > 0 and t - src.rwytotime[i] \
                        > src.dtakeoff:
                    src.rwytotime[i] = t
                    src.rwyline[i] -= 1
                    acid = self._acid(src.name)
                    actype = src.actypes[self.rng.integers(
                        len(src.actypes))]
                    stack.stack(f"CRE {acid},{actype},{rlat},{rlon},"
                                f"{rhdg},0,0")
                    stack.stack(f"{acid} SPD 250")
                    stack.stack(f"{acid} ALT FL100")
                    stack.stack(f"{acid} HDG {rhdg}")
                    self._give_dest(stack, acid, src)
            return
        if n_new == 0:
            return
        # Instant spawns at the source point: ONE traf.create call for the
        # whole batch (single device write sweep on flush); only the
        # guidance follow-ups go through stack command strings.
        alt_ft, spd_kt = src.start_alt_spd(self.rng, n_new)
        if src.incircle and not src.seg:
            hdg = self.rng.uniform(0.0, 360.0, n_new)
        else:
            hdg = np.full(n_new, src.hdg if src.hdg is not None else 0.0)
        acids = [self._acid(src.name) for _ in range(n_new)]
        actypes = [src.actypes[self.rng.integers(len(src.actypes))]
                   for _ in range(n_new)]
        self.sim.traf.create(
            n_new, actypes, acalt=alt_ft * 0.3048,
            acspd=spd_kt * 0.514444, aclat=np.full(n_new, src.lat),
            aclon=np.full(n_new, src.lon), achdg=hdg, acid=acids)
        for k in range(n_new):
            self._give_dest(stack, acids[k], src)

    def _give_dest(self, stack, acid, src):
        if not src.dest:
            return
        name, dlat, dlon = src.dest[self.rng.integers(len(src.dest))]
        if name and not name.startswith("SEGM"):
            stack.stack(f"{acid} DEST {name}")
        else:
            stack.stack(f"{acid} DEST {dlat} {dlon}")
        stack.stack(f"{acid} LNAV ON")

    def _update_drain(self, drn, dt, t):
        """Spawn toward a drain from its origins (trafgenclasses.py:608-682,
        batched)."""
        n_new = self._spawn_count(drn, dt)
        if n_new == 0:
            return
        stack = self.sim.stack
        alt_ft, spd_kt = drn.start_alt_spd(self.rng, n_new)
        lats, lons, hdgs, acids, actypes = [], [], [], [], []
        for _ in range(n_new):
            if drn.orig:
                oname, olat, olon, oincirc = drn.orig[
                    self.rng.integers(len(drn.orig))]
                hdg = _bearing(olat, olon, drn.lat, drn.lon)
                if not oincirc:
                    olat, olon = self.segpos(
                        (_bearing(self.ctrlat, self.ctrlon, olat, olon)))
                    hdg = _bearing(olat, olon, drn.lat, drn.lon)
            else:
                brg = self.rng.uniform(0.0, 360.0)
                olat, olon = self.segpos(brg)
                hdg = (brg + 180.0) % 360.0
            lats.append(olat)
            lons.append(olon)
            hdgs.append(hdg)
            acids.append(self._acid(drn.name))
            actypes.append(drn.actypes[self.rng.integers(
                len(drn.actypes))])
        self.sim.traf.create(
            n_new, actypes, acalt=alt_ft * 0.3048,
            acspd=spd_kt * 0.514444, aclat=np.asarray(lats),
            aclon=np.asarray(lons), achdg=np.asarray(hdgs), acid=acids)
        for acid in acids:
            if not drn.seg:
                stack.stack(f"{acid} DEST {drn.name}")
            else:
                stack.stack(f"{acid} ADDWPT {drn.lat} {drn.lon}")
            stack.stack(f"{acid} LNAV ON")


def _bearing(lat1, lon1, lat2, lon2):
    """Flat-earth bearing [deg 0..360) (trafgenclasses kwikqdrdist use)."""
    dlat = lat2 - lat1
    dlon = (lon2 - lon1 + 180.0) % 360.0 - 180.0
    coslat = np.cos(np.radians(0.5 * (lat1 + lat2)))
    return float(np.degrees(np.arctan2(dlon * coslat, dlat)) % 360.0)
