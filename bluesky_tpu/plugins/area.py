"""Experiment-area plugin: delete aircraft leaving the area, FLST log.

Parity with the reference ``plugins/area.py:47-219``: an experiment area
(existing shape name or ad-hoc box) from which exiting aircraft are
deleted, per-flight efficiency accumulators (2D/3D distance, work done),
the FLST flight-statistics event log written at deletion, and the
AREA / TAXI stack commands.

TPU-first divergences:
* Accumulators are [nmax] arrays on stable slots, integrated at the
  plugin's chunk-edge update from one host sample of gs/vs/alt/thrust —
  with the *actual* elapsed sim time since the previous update (the
  reference multiplies by its nominal dt, plugins/area.py:118-125, which
  drifts if the loop stalls).
* Exit detection is the vectorized areafilter check on the same host
  sample; deletions go through the Traffic facade (mask writes).
"""
import numpy as np

FLST_HEADER = (
    "FLST log - flight statistics: "
    "deletion time [s], callsign, spawn time [s], flight time [s], "
    "2D distance [m], 3D distance [m], work done [J], "
    "lat [deg], lon [deg], alt [m], TAS [m/s], VS [m/s], HDG [deg], "
    "ASAS active [bool], pilot alt [m], pilot TAS [m/s], "
    "pilot VS [m/s], pilot HDG [deg]")


def init_plugin(sim):
    area = Area(sim)

    config = {
        "plugin_name": "AREA",
        "plugin_type": "sim",
        "update_interval": area.dt,
        "update": area.update,
        "reset": area.reset,
    }
    stackfunctions = {
        "AREA": [
            "AREA Shapename/OFF or AREA lat,lon,lat,lon,[top,bottom]",
            "[float/txt,float,float,float,alt,alt]",
            area.set_area,
            "Define experiment area (area of interest)",
        ],
        "TAXI": [
            "TAXI ON/OFF [alt]: OFF auto deletes traffic below 1500 ft",
            "onoff,[alt]",
            area.set_taxi,
            "Ground/low-altitude mode: prevents auto-delete at 1500 ft",
        ],
    }
    return config, stackfunctions


class Area:
    def __init__(self, sim):
        self.sim = sim
        traf = sim.traf
        self.active = False
        self.dt = 0.5                  # [s] area-check interval
        self.name = None
        self.swtaxi = True             # True = no low-altitude auto-delete
        self.swtaxialt = 1500.0 * 0.3048
        nmax = traf.nmax
        self.inside = np.zeros(nmax, dtype=bool)
        self.oldalt = np.zeros(nmax)
        self.distance2d = np.zeros(nmax)
        self.distance3d = np.zeros(nmax)
        self.work = np.zeros(nmax)
        self.create_time = np.zeros(nmax)
        self.last_t = float(sim.simt)
        self.logger = sim.datalog.define_event("FLSTLOG", FLST_HEADER)
        traf.create_hooks.append(self.on_create)
        traf.delete_hooks.append(self.on_delete)

    # ---------------------------------------------------------- lifecycle
    def on_create(self, slots):
        slots = np.atleast_1d(np.asarray(slots))
        t = self.sim.simt
        ac = self.sim.traf.state.ac
        alt = np.asarray(ac.alt)
        self.create_time[slots] = t
        self.oldalt[slots] = alt[slots]
        self.inside[slots] = False
        self.distance2d[slots] = 0.0
        self.distance3d[slots] = 0.0
        self.work[slots] = 0.0

    def on_delete(self, idx):
        for i in np.atleast_1d(np.asarray(idx)):
            self.inside[int(i)] = False

    def reset(self):
        self.active = False
        self.name = None
        self.inside[:] = False
        self.distance2d[:] = 0.0
        self.distance3d[:] = 0.0
        self.work[:] = 0.0
        self.logger.stop()
        self.last_t = float(self.sim.simt)

    # ------------------------------------------------------------- update
    def update(self):
        """Integrate efficiency metrics; delete aircraft that left the
        area, logging their FLST row (plugins/area.py:113-174)."""
        sim = self.sim
        traf = sim.traf
        t = sim.simt
        dt = max(0.0, t - self.last_t)
        self.last_t = t
        if not self.active and self.swtaxi:
            return
        st = traf.state
        active = np.asarray(st.ac.active)
        gs = np.asarray(st.ac.gs)
        vs = np.asarray(st.ac.vs)
        alt = np.asarray(st.ac.alt)
        resultantspd = np.sqrt(gs * gs + vs * vs)
        self.distance2d += dt * gs * active
        self.distance3d += dt * resultantspd * active
        self.work += np.asarray(st.perf.thrust) * dt * resultantspd * active

        # Low-altitude auto-delete when taxi mode is off
        delmask = np.zeros_like(active)
        if not self.swtaxi:
            delmask |= active & (self.oldalt >= self.swtaxialt) \
                & (alt < self.swtaxialt)
            self.oldalt = alt.copy()

        if self.active and self.name is not None:
            lat = np.asarray(st.ac.lat)
            lon = np.asarray(st.ac.lon)
            inside = np.asarray(
                sim.areas.checkInside(self.name, lat, lon, alt)) & active
            leavers = self.inside & ~inside & active
            self.inside = inside
            delmask |= leavers

        delidx = np.where(delmask)[0]
        if len(delidx) == 0:
            return
        ids = [traf.ids[i] for i in delidx]
        st = traf.state
        g = lambda a: np.asarray(a)[delidx]
        self.logger.log(
            sim, ids,
            self.create_time[delidx],
            t - self.create_time[delidx],
            self.distance2d[delidx],
            self.distance3d[delidx],
            self.work[delidx],
            g(st.ac.lat), g(st.ac.lon), g(st.ac.alt),
            g(st.ac.tas), g(st.ac.vs), g(st.ac.hdg),
            g(st.asas.active),
            g(st.pilot.alt), g(st.pilot.tas), g(st.pilot.vs),
            g(st.pilot.hdg))
        traf.delete(delidx)

    # ------------------------------------------------------------ commands
    def set_area(self, *args):
        """AREA Shapename/OFF or AREA lat,lon,lat,lon,[top,bottom]
        (plugins/area.py:177-210)."""
        args = [a for a in args if a is not None]
        if not args:
            return True, ("Area is currently "
                          + ("ON" if self.active else "OFF")
                          + "\nCurrent Area name is: " + str(self.name))
        a0 = args[0]
        if isinstance(a0, str) and not _isfloat(a0) and len(args) == 1:
            name = a0.upper()
            if self.sim.areas.hasArea(name) or self.sim.areas.hasArea(a0):
                self.name = name if self.sim.areas.hasArea(name) else a0
                self.active = True
                self.inside[:] = False
                self.logger.start(self.sim)
                return True, f"Area is set to {self.name}"
            if name in ("OFF", "OF"):
                if self.name is not None:
                    self.sim.areas.deleteArea(self.name)
                self.logger.stop()
                self.active = False
                self.name = None
                return True, "Area is switched OFF"
            return False, ("Shapename unknown. Please create shapename "
                           "first or shapename is misspelled!")
        if len(args) >= 4:
            try:
                coords = [float(a) for a in args[:4]]
                bounds = [float(a) for a in args[4:6]]
            except (TypeError, ValueError):
                return False, ("Incorrect arguments\n"
                               "AREA Shapename/OFF or "
                               "AREA lat,lon,lat,lon,[top,bottom]")
            self.active = True
            self.name = "DELAREA"
            self.sim.areas.defineArea(self.name, "BOX", coords, *bounds)
            self.inside[:] = False
            self.logger.start(self.sim)
            return True, f"Area is ON. Area name is: {self.name}"
        return False, ("Incorrect arguments\nAREA Shapename/OFF or "
                       "AREA lat,lon,lat,lon,[top,bottom]")

    def set_taxi(self, flag, alt=None):
        """TAXI ON/OFF [alt] (plugins/area.py:212-215)."""
        self.swtaxi = bool(flag)
        if alt is not None:
            self.swtaxialt = float(alt)
        self.oldalt = np.asarray(self.sim.traf.state.ac.alt).copy()
        return True


def _isfloat(s):
    try:
        float(s)
        return True
    except (TypeError, ValueError):
        return False
