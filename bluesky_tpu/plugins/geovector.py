"""Geovectoring: per-area speed/track/vertical-speed interval constraints.

Parity with the reference ``plugins/geovector.py``: for a named area
(BOX/POLY/CIRCLE), clamp each component of the commanded 3D velocity
vector of aircraft inside the area to an allowed interval — ground
speed [gsmin, gsmax] (given as CAS at the aircraft altitude), track
[trkmin, trkmax] (interval < 180 deg to stay unambiguous), vertical
speed [vsmin, vsmax] — applied in the preupdate hook each interval.

TPU-first: the clamp is one masked device write over the padded arrays
per geovector (the reference does boolean-indexed NumPy assignments);
the area test runs on the host sample like the other chunk-edge
subsystems.
"""
import numpy as np

from ..ops import aero


def init_plugin(sim):
    gv = GeoVector(sim)
    config = {
        "plugin_name": "GEOVECTOR",
        "plugin_type": "sim",
        "update_interval": 1.0,
        "preupdate": gv.preupdate,
        "reset": gv.reset,
    }
    stackfunctions = {
        "GEOVECTOR": [
            "GEOVECTOR area,[gsmin,gsmax,trkmin,trkmax,vsmin,vsmax]",
            "txt,[spd,spd,hdg,hdg,vspd,vspd]",
            gv.defgeovec,
            "Define a geovector for an area defined with "
            "BOX/POLY(ALT)/CIRCLE",
        ],
        "DELGEOVECTOR": [
            "DELGEOVECTOR area",
            "txt",
            gv.delgeovec,
            "Remove the geovector from an area",
        ],
    }
    return config, stackfunctions


def _degto180(d):
    return (np.asarray(d) + 180.0) % 360.0 - 180.0


class GeoVector:
    def __init__(self, sim):
        self.sim = sim
        self.geovecs = []    # [area, gsmin, gsmax, trkmin, trkmax,
        #                       vsmin, vsmax]

    def reset(self):
        self.geovecs = []

    def defgeovec(self, area="", spdmin=None, spdmax=None, trkmin=None,
                  trkmax=None, vspdmin=None, vspdmax=None):
        """GEOVECTOR area,[constraints] (geovector.py defgeovec)."""
        if not area:
            return False, "We need an area"
        if all(v is None for v in (spdmin, spdmax, trkmin, trkmax,
                                   vspdmin, vspdmax)):
            # No values: report the current vector for the area
            for vec in self.geovecs:
                if vec[0] == area.upper():
                    return True, f"GEOVECTOR {area}: {vec[1:]}"
            return False, f"No geovector found for {area}"
        if not self.sim.areas.hasArea(area.upper()):
            return False, f"Area {area} not found"
        self.delgeovec(area)
        self.geovecs.append([area.upper(), spdmin, spdmax, trkmin,
                             trkmax, vspdmin, vspdmax])
        return True

    def delgeovec(self, area=""):
        n0 = len(self.geovecs)
        self.geovecs = [v for v in self.geovecs if v[0] != area.upper()]
        return True if len(self.geovecs) < n0 else \
            (False, f"No geovector found for {area}")

    # ------------------------------------------------------------- update
    def preupdate(self):
        """Apply every geovector (geovector.py applygeovec), one masked
        device write per constrained field."""
        if not self.geovecs:
            return
        import jax.numpy as jnp
        sim = self.sim
        traf = sim.traf
        st = traf.state
        ac = st.ac
        lat = np.asarray(ac.lat)
        lon = np.asarray(ac.lon)
        alt = np.asarray(ac.alt)
        active = np.asarray(ac.active)
        updates = {}

        def arr(name):
            if name not in updates:
                updates[name] = np.asarray(getattr(ac, name)).copy()
            return updates[name]

        aptrk = None
        for (area, gsmin, gsmax, trkmin, trkmax,
             vsmin, vsmax) in self.geovecs:
            if not sim.areas.hasArea(area):
                continue
            inside = np.asarray(sim.areas.checkInside(
                area, lat, lon, alt)) & active
            if not inside.any():
                continue
            if gsmin is not None:
                casmin = np.asarray(aero.vtas2cas(
                    jnp.full(len(alt), gsmin), jnp.asarray(alt)))
                sel = inside & (arr("selspd") < casmin)
                arr("selspd")[sel] = casmin[sel]
            if gsmax is not None:
                casmax = np.asarray(aero.vtas2cas(
                    jnp.full(len(alt), gsmax), jnp.asarray(alt)))
                sel = inside & (arr("selspd") > casmax)
                arr("selspd")[sel] = casmax[sel]
            if trkmin is not None and trkmax is not None:
                if aptrk is None:
                    aptrk = np.asarray(st.ap.trk).copy()
                trk = np.asarray(ac.trk)
                usemin = inside & (_degto180(trk - trkmin) < 0.0)
                usemax = inside & (_degto180(trk - trkmax) > 0.0)
                aptrk[usemin] = trkmin
                aptrk[usemax] = trkmax
            if vsmin is not None:
                vs = np.asarray(ac.vs)
                sel = inside & (vs < vsmin)
                arr("selvs")[sel] = vsmin
                arr("selalt")[sel] = alt[sel] + np.sign(vsmin) * 200.0 \
                    * aero.ft
            if vsmax is not None:
                vs = np.asarray(ac.vs)
                sel = inside & (vs > vsmax)
                arr("selvs")[sel] = vsmax
                arr("selalt")[sel] = alt[sel] + np.sign(vsmax) * 200.0 \
                    * aero.ft

        if updates or aptrk is not None:
            newac = ac.replace(**{k: jnp.asarray(v, getattr(ac, k).dtype)
                                  for k, v in updates.items()})
            newst = st.replace(ac=newac)
            if aptrk is not None:
                newst = newst.replace(ap=st.ap.replace(
                    trk=jnp.asarray(aptrk, st.ap.trk.dtype)))
            traf.state = newst
