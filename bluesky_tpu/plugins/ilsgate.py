"""ILS approach-gate plugin.

Parity with the reference ``plugins/ilsgate.py``: defines a triangular
POLYALT area (50 nm cone, +/-20 deg, below 4000 ft) pointing away from
a runway threshold, for approach-sequencing experiments.

The reference reads thresholds from ``navdb.rwythresholds`` (parsed
from apt.zip, which this data snapshot does not ship — the reference
would find nothing either).  Extension: an explicit
``ILSGATE name,lat,lon,hdg`` form defines the gate from a given
threshold so the capability works without the proprietary data.
"""
import numpy as np

from ..ops import aero, geo


def init_plugin(sim):
    gate = IlsGate(sim)
    config = {
        "plugin_name": "ILSGATE",
        "plugin_type": "sim",
        "update_interval": 0.0,
        "reset": gate.reset,
    }
    stackfunctions = {
        "ILSGATE": [
            "ILSGATE airport/RWYxx or ILSGATE name,lat,lon,hdg",
            "txt,[lat,lon,hdg]",
            gate.ilsgate,
            "Define an ILS approach area for a runway",
        ],
    }
    return config, stackfunctions


class IlsGate:
    CONE_LENGTH = 50.0      # [nm]
    CONE_ANGLE = 20.0       # [deg]

    def __init__(self, sim):
        self.sim = sim
        self.gates = []

    def reset(self):
        for name in self.gates:
            self.sim.areas.deleteArea(name)
        self.gates = []

    def ilsgate(self, rwyname, lat=None, lon=None, hdg=None):
        if lat is None:
            if "/" not in rwyname:
                return False, f"Argument is not a runway: {rwyname}"
            apt, rwy = rwyname.upper().split("/RW")
            rwy = rwy.lstrip("Y")
            thresholds = getattr(self.sim.navdb, "rwythresholds", {})
            thr = thresholds.get(apt, {}).get(rwy)
            if thr is None:
                return False, (f"Runway {rwyname} not in the navdata "
                               "(no apt.zip in this data snapshot); use "
                               "ILSGATE name,lat,lon,hdg")
            lat, lon, hdg = thr[0], thr[1], thr[2]
        name = "ILS" + rwyname.upper().replace("/", "")
        lat1, lon1 = (float(x) for x in geo.qdrpos(
            lat, lon, hdg - 180.0 + self.CONE_ANGLE,
            self.CONE_LENGTH))   # dist in [nm]
        lat2, lon2 = (float(x) for x in geo.qdrpos(
            lat, lon, hdg - 180.0 - self.CONE_ANGLE,
            self.CONE_LENGTH))
        coords = [float(lat), float(lon), lat1, lon1, lat2, lon2]
        self.sim.areas.defineArea(name, "POLY", coords,
                                  top=4000.0 * aero.ft, bottom=-1e9)
        self.gates.append(name)
        return True, f"ILS gate {name} defined"
