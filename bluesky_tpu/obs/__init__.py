"""Unified telemetry for the sim/worker/server stack (ISSUE-11).

Two dependency-free halves:

* ``obs.metrics`` — counters / gauges / fixed-bucket histograms in a
  ``Registry``.  Every ``Simulation`` owns one (so two sims in one
  process never mix series), the server owns one for broker-side
  series plus a second *fleet* registry that folds the metric deltas
  riding worker heartbeats.  ``METRICS DUMP`` / the server ``METRICS``
  event export them; ``settings.metrics_export_path`` adds an
  atomically-rewritten Prometheus text dump.

* ``obs.trace`` — the flight recorder: a bounded ring of typed span
  events with correlation tags (piece id, world index, chunk seq, mesh
  epoch), dumped on demand (``TRACE DUMP``) or automatically on
  guard/mesh trips as Chrome/Perfetto trace-event JSON.
  ``scripts/trace_report.py`` merges dumps from several processes onto
  one timeline.

Overhead contract (docs/OBSERVABILITY.md): recorder off ⇒ zero added
device ops and bit-identical stepped state; recorder on ⇒ <2% wall
overhead (BENCH_OBS.json).
"""
from .metrics import Registry, get_registry          # noqa: F401
from .trace import Recorder, get_recorder            # noqa: F401
