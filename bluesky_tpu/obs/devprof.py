"""Device-level observability (ISSUE-12): compile telemetry, memory
watermarks, donation accounting and on-demand device-trace windows.

The PR-11 flight recorder stops at host-side span timestamps; this
module answers the questions those spans can only hint at:

* **What compiles, when?**  A module-level ``jax.monitoring`` duration
  listener feeds per-compile trace/lower/backend histograms into every
  subscribed registry, and host-side cache accounting keyed on
  ``(program, nsteps, nmax, ndev)`` splits compile-cache misses into
  *ladder warm-up* (``nsteps`` on the sim's ``CHUNK_LADDER``) vs
  *off-ladder recompiles* (a CHUNKSTEPS value outside the ladder, a
  changed nmax bucket, a resized mesh).  ``METRICS DUMP`` / ``HEALTH``
  surface both, so a mid-run recompile storm is visible.

* **How close to memory limits?**  ``sample_memory()`` walks
  ``jax.live_arrays()`` at chunk edges (throttled by the
  ``devprof_mem_dt`` knob) into per-device live-byte gauges plus a
  self-tracked peak — on backends whose ``device.memory_stats()``
  report a peak the larger of the two wins.  An optional donation
  check counts input buffers the runner expected XLA to reuse but
  which survived the dispatch (``devprof_donation_check``; forces a
  host sync, debug only).

* **Where does a chunk's wall time go?**  ``PROFILE DEVICE [n] [dir]``
  opens a window over the next ``n`` chunk dispatches: a
  ``jax.profiler`` trace brackets them (the XLA trace lands in
  ``dir``), and each windowed chunk is timed in three sub-sections —
  *compute* (dispatch → device done), *halo* (the pre-dispatch
  spatial-sort / halo-exchange refresh) and *edge* (host edge-retire
  work) — emitted as ``devprof_chunk`` complete events on the flight
  recorder plus three registry histograms.  The window itself is a
  ``device_profile`` span tagged with the trace dir, so
  ``scripts/devprof_report.py`` can merge the host dumps with the
  XLA ``*.trace.json.gz`` onto one Perfetto timeline.  Windowed
  dispatches block on the device (that is the point: attribution
  needs the fence), so the window briefly serializes the pipeline.

Contract (docs/OBSERVABILITY.md): with every feature off, the hooks
are attribute checks only — zero device ops, bit-identical stepped
state, covered by the obs_smoke <2% overhead gate.
"""
import os
import threading
import time
import weakref

# jax.monitoring event names (jax 0.4.x) -> histogram series.  Durations
# arrive in seconds; the registry ladders are ms.
_COMPILE_EVENTS = {
    "/jax/core/compile/jaxpr_trace_duration": "devprof_compile_trace_ms",
    "/jax/core/compile/jaxpr_to_mlir_module_duration":
        "devprof_compile_lower_ms",
    "/jax/core/compile/backend_compile_duration":
        "devprof_compile_backend_ms",
}

# Byte-scale bucket ladder for anything we might histogram in bytes —
# the gauges don't need it, but compile durations can hit many seconds.
COMPILE_MS_BUCKETS = (1.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
                      1000.0, 2500.0, 5000.0, 10000.0, 30000.0, 60000.0)

_SUBSCRIBERS = weakref.WeakSet()     # registries fed by the listener
_LISTENER_LOCK = threading.Lock()
_LISTENER_INSTALLED = False


def _on_compile_event(event, duration_secs, **kw):
    name = _COMPILE_EVENTS.get(event)
    if name is None:
        return
    ms = duration_secs * 1e3
    for reg in list(_SUBSCRIBERS):
        reg.histogram(name, buckets=COMPILE_MS_BUCKETS).observe(ms)
        if event.endswith("backend_compile_duration"):
            reg.counter("devprof_backend_compiles").inc()


def install_compile_listener(registry):
    """Subscribe ``registry`` to the process-wide jax.monitoring compile
    events.  The listener itself is registered once per process (JAX
    has no unregister API); subscription is a WeakSet so dead sims drop
    out on their own.  Returns False when the monitoring API is absent
    (older/stubbed jax) — telemetry degrades to the host-side cache
    accounting only."""
    global _LISTENER_INSTALLED
    _SUBSCRIBERS.add(registry)
    if _LISTENER_INSTALLED:
        return True
    with _LISTENER_LOCK:
        if _LISTENER_INSTALLED:
            return True
        try:
            from jax import monitoring
            monitoring.register_event_duration_secs_listener(
                _on_compile_event)
        except Exception:
            return False
        _LISTENER_INSTALLED = True
    return True


class DevProf:
    """Per-sim device observability.  Always present on a Simulation
    (``sim.devprof``); every hook early-outs on plain attribute checks
    when its feature is off, so the disabled path adds no device ops.
    """

    def __init__(self, obs, recorder, ladder=()):
        self.obs = obs
        self.recorder = recorder
        self.ladder = tuple(int(x) for x in ladder)
        self._seen = set()           # (program, nsteps, nmax, ndev)
        self._peaks = {}             # device id -> peak live bytes seen
        self._last_mem = -1e18       # monotonic stamp of last sample
        self._window = None          # active profile-window dict
        self._window_req = None      # (n_chunks, logdir) pending
        self.windows = []            # completed-window records
        from .. import settings
        if bool(getattr(settings, "devprof_compile_telemetry", True)):
            install_compile_listener(obs)
        obs.counter("devprof_cache_hits",
                    help="chunk dispatches whose (program, nsteps, "
                         "nmax, ndev) key was already compiled")
        obs.counter("devprof_cache_misses_ladder",
                    help="first-seen dispatch keys with nsteps on the "
                         "chunk ladder (expected warm-up compiles)")
        obs.counter("devprof_cache_misses_offladder",
                    help="first-seen dispatch keys OFF the chunk "
                         "ladder (accidental/mid-run recompiles)")

    # ------------------------------------------------ compile telemetry
    def note_dispatch(self, program, nsteps, nmax, ndev):
        """Host-side compile-cache accounting for one chunk dispatch.
        jit caches on (program identity, static args, input avals); the
        key below is the sim-level projection of that, so a first-seen
        key == one real compile.  A key is counted as a miss exactly
        once (set semantics), which is what the acceptance test pins."""
        from .. import settings
        if not bool(getattr(settings, "devprof_compile_telemetry", True)):
            return
        key = (program, int(nsteps), int(nmax), int(ndev))
        if key in self._seen:
            self.obs.get("devprof_cache_hits").inc()
            return
        self._seen.add(key)
        if int(nsteps) in self.ladder:
            self.obs.get("devprof_cache_misses_ladder").inc()
        else:
            self.obs.get("devprof_cache_misses_offladder").inc()
            self.recorder.instant("devprof_recompile", cat="devprof",
                                  program=program, nsteps=int(nsteps),
                                  nmax=int(nmax), ndev=int(ndev))

    def compile_summary(self):
        """One-line HEALTH/METRICS summary of the cache accounting."""
        g = lambda n: int(getattr(self.obs.get(n), "value", 0) or 0)
        bc = self.obs.get("devprof_backend_compiles")
        parts = [f"ladder warm-up {g('devprof_cache_misses_ladder')}",
                 f"off-ladder {g('devprof_cache_misses_offladder')}",
                 f"hits {g('devprof_cache_hits')}"]
        if bc is not None:
            parts.append(f"backend compiles {int(bc.value)}")
        return ", ".join(parts)

    # ------------------------------------------------ memory watermarks
    def sample_memory(self, now=None, force=False):
        """Per-device live-bytes + peak gauges from ``jax.live_arrays``
        (throttled by the ``devprof_mem_dt`` knob; 0 = off).  Returns
        the per-device live-byte dict, or None when skipped."""
        from .. import settings
        dt = float(getattr(settings, "devprof_mem_dt", 0.0))
        if dt <= 0.0 and not force:
            return None
        now = time.monotonic() if now is None else now
        if not force and now - self._last_mem < dt:
            return None
        self._last_mem = now
        import jax
        per = {}
        for arr in jax.live_arrays():
            try:
                for sh in arr.addressable_shards:
                    did = sh.device.id
                    per[did] = per.get(did, 0) + int(sh.data.nbytes)
            except Exception:
                devs = list(getattr(arr, "devices", lambda: [])())
                if not devs:
                    continue
                share = int(arr.nbytes) // len(devs)
                for d in devs:
                    per[d.id] = per.get(d.id, 0) + share
        total = 0
        for did, nbytes in sorted(per.items()):
            total += nbytes
            peak = max(self._peaks.get(did, 0), nbytes)
            # A backend that reports real allocator stats knows the true
            # peak (transients between our edge samples); trust it when
            # larger.  CPU reports None — the self-tracked peak stands.
            try:
                stats = jax.devices()[did].memory_stats()
                if stats and stats.get("peak_bytes_in_use"):
                    peak = max(peak, int(stats["peak_bytes_in_use"]))
            except Exception:
                pass
            self._peaks[did] = peak
            self.obs.gauge(f"devprof_live_bytes_dev{did}",
                           help="live device bytes at last chunk-edge "
                                "sample").set(nbytes)
            self.obs.gauge(f"devprof_peak_bytes_dev{did}",
                           help="peak live device bytes seen").set(peak)
        self.obs.gauge("devprof_live_bytes_total",
                       help="live device bytes, all devices").set(total)
        return per

    def watermarks(self):
        """{device id: (live, peak)} from the gauges (last sample)."""
        out = {}
        for did, peak in sorted(self._peaks.items()):
            g = self.obs.get(f"devprof_live_bytes_dev{did}")
            out[did] = (int(g.value) if g else 0, int(peak))
        return out

    def check_donation(self, state_in):
        """Count input buffers a donating dispatch left alive (XLA
        declined the donation — usually a layout/alias mismatch).
        Forces nothing itself, but only meaningful after the dispatch
        has been consumed; gated on ``devprof_donation_check``."""
        from .. import settings
        if not bool(getattr(settings, "devprof_donation_check", False)):
            return 0
        import jax
        missed = 0
        for leaf in jax.tree_util.tree_leaves(state_in):
            if hasattr(leaf, "is_deleted") and not leaf.is_deleted():
                missed += 1
        if missed:
            self.obs.counter(
                "devprof_donation_missed",
                help="donated input buffers XLA re-allocated instead "
                     "of reusing").inc(missed)
            self.recorder.instant("devprof_donation_missed",
                                  cat="devprof", buffers=missed)
        return missed

    # ------------------------------------------------- profile windows
    @property
    def window_active(self):
        return self._window is not None

    def request_window(self, n_chunks=1, logdir=None):
        """Arm a device-trace window over the next ``n_chunks`` chunk
        dispatches (the PROFILE DEVICE command).  Returns the resolved
        trace dir."""
        from .. import settings
        if not logdir:
            base = str(getattr(settings, "trace_dir", "") or "") \
                or str(getattr(settings, "log_path", "output"))
            logdir = os.path.join(base, "devprof")
        self._window_req = (max(int(n_chunks), 1), logdir)
        return logdir

    def begin_chunk(self, seq):
        """Dispatch-side hook: start the armed window (if any) and
        report whether this chunk is inside one.  Admission is capped
        at ``n`` — the pipeline dispatches chunk k+1 before chunk k's
        edge retires, so without the cap an extra chunk would slip in
        while the last windowed edges drain."""
        if self._window_req is not None and self._window is None:
            n, logdir = self._window_req
            self._window_req = None
            try:
                import jax
                os.makedirs(logdir, exist_ok=True)
                jax.profiler.start_trace(logdir)
            except Exception as e:
                self.recorder.instant("device_profile_failed",
                                      cat="devprof", error=str(e)[:200])
                return False
            self._window = {"n": n, "left": n, "admitted": 0,
                            "dir": logdir, "seq0": seq,
                            "t0": time.perf_counter(), "chunks": {}}
        w = self._window
        if w is None or w["admitted"] >= w["n"]:
            return False
        w["admitted"] += 1
        return True

    def note_chunk(self, seq, chunk, compute_ms, halo_ms):
        """Record the dispatch-side sub-sections of a windowed chunk
        (edge_ms arrives later via note_edge)."""
        w = self._window
        if w is None:
            return
        w["chunks"][seq] = {"chunk": chunk,
                            "compute_ms": round(float(compute_ms), 3),
                            "halo_ms": round(float(halo_ms), 3),
                            "t0": time.perf_counter()}
        self.obs.histogram(
            "devprof_compute_ms",
            help="windowed chunk device compute wall ms").observe(
                compute_ms)
        self.obs.histogram(
            "devprof_halo_ms",
            help="windowed chunk pre-dispatch sort/halo wall ms"
        ).observe(halo_ms)

    def note_edge(self, seq, edge_ms):
        """Edge-retire hook: completes one windowed chunk's attribution
        and closes the window after the n-th edge."""
        w = self._window
        if w is None:
            return
        c = w["chunks"].get(seq)
        if c is None:
            return
        c["edge_ms"] = round(float(edge_ms), 3)
        self.obs.histogram(
            "devprof_edge_ms",
            help="windowed chunk host edge-retire wall ms").observe(
                edge_ms)
        rec = self.recorder
        if rec.enabled:
            rec.complete("devprof_chunk", rec.wall_us(c["t0"]),
                         max(edge_ms, 0.001) * 1e3, cat="devprof",
                         seq=seq, chunk=c["chunk"],
                         compute_ms=c["compute_ms"],
                         halo_ms=c["halo_ms"], edge_ms=c["edge_ms"])
        w["left"] -= 1
        if w["left"] <= 0:
            self._end_window()

    def _end_window(self):
        w, self._window = self._window, None
        if w is None:
            return
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception as e:
            self.recorder.instant("device_profile_failed",
                                  cat="devprof", error=str(e)[:200])
        t1 = time.perf_counter()
        rec = self.recorder
        rec.complete("device_profile", rec.wall_us(w["t0"]),
                     (t1 - w["t0"]) * 1e6, cat="devprof",
                     dir=w["dir"], n_chunks=w["n"], seq0=w["seq0"])
        record = {"dir": w["dir"], "n_chunks": w["n"],
                  "seq0": w["seq0"],
                  "wall_s": round(t1 - w["t0"], 4),
                  "chunks": w["chunks"]}
        self.windows.append(record)
        self.obs.counter("devprof_windows",
                         help="completed PROFILE DEVICE windows").inc()
        return record

    def abort_window(self):
        """Close a half-open window (drain/shutdown paths)."""
        if self._window is not None:
            self._window["left"] = 0
            self._end_window()
        self._window_req = None
