"""Flight recorder: a bounded ring of typed span events, dumped as
Chrome/Perfetto trace-event JSON.

Design points (docs/OBSERVABILITY.md has the user guide):

* **Per-process singleton.**  One ``Recorder`` per process covers the
  sim thread, the node event loop and (in a broker process) the server
  thread — ``pid`` separates processes on the merged timeline, ``tid``
  separates threads inside one.

* **Off = free.**  ``span()`` on a disabled recorder returns a shared
  no-op context manager before touching any argument-dependent work,
  and no instrumentation site adds device ops — the stepped state is
  bit-identical with the recorder off (pinned by tests/test_obs.py).

* **Wall-anchored timestamps.**  Events are stamped with
  ``perf_counter`` (monotonic, ns-resolution) shifted by a per-process
  wall anchor captured at import, so dumps from different processes
  land on ONE timeline when ``scripts/trace_report.py`` merges them
  (cross-process skew = NTP-level, fine for ms-scale spans).

* **Typed spans + correlation tags.**  ``SPAN_TYPES`` names the
  vocabulary; tags carry the same correlation ids the BATCH journal
  uses — ``piece`` (scenario name), ``world`` (index in a pack),
  ``seq`` (host-side chunk sequence number), ``epoch`` (mesh epoch) —
  so one piece's sim, worker and server spans line up.

* **Auto-dump.**  Guard/mesh trips dump the ring (throttled) so the
  events *leading up to* an incident survive it.
"""
import json
import os
import threading
import time
from collections import deque

# The span vocabulary.  Unknown names are not rejected (plugins may
# add their own), but everything the core emits is listed here and in
# docs/OBSERVABILITY.md.
SPAN_TYPES = ("chunk_dispatch", "chunk_edge", "sort_refresh",
              "snapshot_capture", "mesh_check", "hedge", "demux",
              "journal_append", "opt_step", "pack_fill",
              "device_profile", "devprof_chunk")

# Wall anchor: perf_counter() + _EPOCH == time.time() at import, so
# every process's event clocks share one (NTP-aligned) origin.
_EPOCH = time.time() - time.perf_counter()


def _now_us():
    return (time.perf_counter() + _EPOCH) * 1e6


class _NullSpan:
    """Shared no-op context manager for the disabled path."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("rec", "name", "cat", "tags", "t0")

    def __init__(self, rec, name, cat, tags):
        self.rec = rec
        self.name = name
        self.cat = cat
        self.tags = tags

    def __enter__(self):
        self.t0 = _now_us()
        return self

    def __exit__(self, *exc):
        t1 = _now_us()
        self.rec._append({"name": self.name, "cat": self.cat,
                          "ph": "X", "ts": self.t0,
                          "dur": t1 - self.t0,
                          "pid": os.getpid(),
                          "tid": threading.get_ident(),
                          "args": self.tags})
        return False


class Recorder:
    """Bounded ring of trace events + Perfetto JSON dump."""

    def __init__(self, maxlen=None):
        if maxlen is None:
            from .. import settings
            maxlen = int(getattr(settings, "trace_ring_size", 4096))
        self.enabled = False
        self._ring = deque(maxlen=max(int(maxlen), 16))
        self._lock = threading.Lock()
        self._dump_n = 0
        self._last_autodump = -1e18
        self.dumps = []              # paths written this process

    # ---------------------------------------------------------- control
    def enable(self, on=True):
        self.enabled = bool(on)
        return self.enabled

    def disable(self):
        self.enabled = False

    def clear(self):
        with self._lock:
            self._ring.clear()

    def __len__(self):
        return len(self._ring)

    @property
    def maxlen(self):
        return self._ring.maxlen

    # ---------------------------------------------------------- record
    def _append(self, ev):
        with self._lock:
            self._ring.append(ev)

    def span(self, name, cat="sim", **tags):
        """Duration event context manager; no-op when disabled."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, cat, tags)

    def instant(self, name, cat="sim", **tags):
        """Instant event (guard trip, mesh_lost, hedge fired...)."""
        if not self.enabled:
            return
        self._append({"name": name, "cat": cat, "ph": "i",
                      "ts": _now_us(), "s": "p",
                      "pid": os.getpid(),
                      "tid": threading.get_ident(), "args": tags})

    def complete(self, name, t0_us, dur_us, cat="sim", **tags):
        """Record an already-timed duration (for call sites that keep
        their own perf_counter stamps, e.g. the chunk-latency path)."""
        if not self.enabled:
            return
        self._append({"name": name, "cat": cat, "ph": "X",
                      "ts": t0_us, "dur": dur_us, "pid": os.getpid(),
                      "tid": threading.get_ident(), "args": tags})

    @staticmethod
    def wall_us(perf_s=None):
        """Wall-anchored µs for a perf_counter() stamp (default: now)."""
        if perf_s is None:
            return _now_us()
        return (perf_s + _EPOCH) * 1e6

    # ------------------------------------------------------------- dump
    def dump(self, path=None, reason="manual", proc="sim"):
        """Write the ring as Chrome trace-event JSON.  Returns the path
        (atomic tmp+replace write), or None when the ring is empty.
        The ring is NOT cleared: a later dump extends the story."""
        with self._lock:
            events = list(self._ring)
        if not events:
            return None
        if path is None:
            from .. import settings
            d = str(getattr(settings, "trace_dir", "") or "") \
                or str(getattr(settings, "log_path", "output"))
            os.makedirs(d, exist_ok=True)
            self._dump_n += 1
            path = os.path.join(
                d, f"trace-{proc}-{os.getpid()}-{self._dump_n:03d}"
                   f"-{reason}.json")
        else:
            pd = os.path.dirname(path)
            if pd:
                os.makedirs(pd, exist_ok=True)
        doc = {"traceEvents": events, "displayTimeUnit": "ms",
               "otherData": {"proc": proc, "pid": os.getpid(),
                             "reason": reason,
                             "ring": [len(events), self.maxlen]}}
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        self.dumps.append(path)
        return path

    def auto_dump(self, reason, proc="sim"):
        """Throttled incident dump (guard/mesh trips): at most one per
        second so a trip storm can't fill the disk; honours the
        ``trace_autodump`` knob."""
        if not self.enabled:
            return None
        from .. import settings
        if not bool(getattr(settings, "trace_autodump", True)):
            return None
        now = time.monotonic()
        if now - self._last_autodump < 1.0:
            return None
        self._last_autodump = now
        try:
            return self.dump(reason=reason, proc=proc)
        except OSError:
            return None          # a bad trace dir never kills the run


_RECORDER = None
_RECORDER_LOCK = threading.Lock()


def get_recorder():
    """The per-process recorder singleton."""
    global _RECORDER
    if _RECORDER is None:
        with _RECORDER_LOCK:
            if _RECORDER is None:
                _RECORDER = Recorder()
    return _RECORDER
