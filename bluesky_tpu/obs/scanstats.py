"""In-scan telemetry: device-side stats folded through the compiled step.

``EdgeTelemetry`` (core/step.py) packs only the FINAL step's values, so
a 1000-step FF chunk exposes 0.1% of the simulated dynamics to
METRICS/HEALTH/the recorder — conflict bursts, closest-approach minima
and envelope saturation between edges are lost.  ``ScanStats`` closes
that gap the way large-scale simulators instrument in-kernel counters
(QarSUMO's per-step congestion statistics, D-AWSIM's per-partition
occupancy telemetry): a small accumulator pytree rides the chunk-scan
CARRY, folded once per step from the post-step state, and is emitted
once per chunk as extra non-donated outputs next to the telemetry pack.
Zero host syncs are added inside the scan; the host pulls the pack at
the chunk edge it already retires.

Contracts (tests/test_scanstats.py, tests/test_hlo_collectives.py):

* **Off path is free.**  The fold only exists behind the hashable
  ``SimConfig.scanstats`` static flag; with it False the chunk scan
  traces the exact pre-existing HLO (the obs_smoke parity hash pins the
  stepped state bit-identical either way — folding never writes state).
* **Fold-exact.**  Every field is a sum/min/max/histogram fold, so a
  20-step chunk's stats equal the reduction of twenty 1-step-chunk
  packs (``reduce_packs``) bit-exactly: counts are int32, mins/maxes
  are order-free, and int sums are associative.
* **No new collectives.**  Scalar folds (conflict/LoS counts) consume
  ``asas.nconf_cur``/``nlos_cur``, which the sharded CD kernels already
  reduce; per-aircraft folds stay ``[P]`` PER-DEVICE PARTIALS via a
  ``reshape(P, nmax // P)`` row split that GSPMD keeps local (shards
  align with rows), reduced host-side after the edge pull.  Pair-gather
  stats (``min_sep_m``) are computed only when ``cd_mesh is None`` —
  a gather into a sharded array would lower to an all-gather — and
  report +inf under a mesh (documented in docs/OBSERVABILITY.md).

Semantics under sharding: ``engaged_peak``/``occ_peak`` are per-partial
peaks over the chunk.  A peak of a global sum is NOT derivable from
per-device peaks (max_t of a sum != sum of max_t), so the host-side
``sum`` over partials is exact single-device and an upper bound on the
fleet-wide peak under spatial stripes — per-stripe peaks themselves are
the capacity-ladder signal (ROADMAP items 1-2, 5).
"""
from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

#: Per-step conflict/LoS count bucket ladder (upper bounds; one extra
#: overflow bucket on device and in the registry histogram).  Fine at
#: the low end — HEALTH cares whether a chunk saw 0, a couple, or a
#: burst of conflicts — log-spaced into large-N territory.
COUNT_BUCKETS = (0.0, 1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0,
                 500.0, 1000.0, 2000.0, 5000.0)

#: Saturation epsilons: ``perf.limits`` CLIPS, so a saturated command
#: sits exactly on the bound up to the CAS<->TAS round-trip error; the
#: epsilon only needs to cover float noise, not physics.
SAT_EPS_MS = 0.05        # [m/s] CAS round-trip tolerance at vmin/vmax
SAT_EPS_M = 0.5          # [m] altitude tolerance at hmax

_RE_M = 6371000.0        # mean-earth radius for the flat-earth distance


class ScanStats(NamedTuple):
    """Per-chunk accumulator pytree (the scan-carry resident).

    Scalar fields fold values that are already replicated under any
    shard mode; ``[P]`` fields are per-device partials (P = mesh size
    when a device mesh divides nmax, else 1) reduced host-side.
    """
    steps: jnp.ndarray           # [] int32 — steps folded
    conf_peak: jnp.ndarray       # [] int32 — max per-step conflict count
    conf_sum: jnp.ndarray        # [] int32 — sum of per-step counts
    conf_hist: jnp.ndarray       # [B+1] int32 — bucketed per-step counts
    los_peak: jnp.ndarray        # [] int32
    los_sum: jnp.ndarray         # [] int32
    los_hist: jnp.ndarray        # [B+1] int32
    engaged_peak: jnp.ndarray    # [P] int32 — peak resolver-engaged rows
    occ_peak: jnp.ndarray        # [P] int32 — peak per-stripe occupancy
    clamp_sat: jnp.ndarray       # [P] int32 — envelope-saturated row-steps
    live_rowsteps: jnp.ndarray   # [P] int32 — live row-steps (denominator)
    min_sep_m: jnp.ndarray       # [P] f32 — min engaged-pair separation
    headroom_min_m: jnp.ndarray  # [P] f32 — min live-row (hmax - alt)


#: Host-side reduction schema (``reduce_packs`` + the fold oracle).
SUM_FIELDS = ("steps", "conf_sum", "conf_hist", "los_sum", "los_hist",
              "clamp_sat", "live_rowsteps")
MAX_FIELDS = ("conf_peak", "los_peak", "engaged_peak", "occ_peak")
MIN_FIELDS = ("min_sep_m", "headroom_min_m")


def n_partials(cfg, nmax: int) -> int:
    """How many per-device partials the ``[P]`` folds keep: the mesh
    size when a device mesh is configured and divides nmax (the row
    split then aligns with the 'ac' shards, so per-partial reductions
    stay local), else 1.  A non-dividing mesh is refused — the sharded
    preparation paths guarantee divisibility, so this only fires on a
    hand-built config."""
    mesh = cfg.cd_mesh
    if mesh is None:
        return 1
    p = int(dict(mesh.shape).get(cfg.cd_mesh_axis, 1))
    if p <= 1:
        return 1
    if nmax % p:
        raise ValueError(
            f"scanstats: nmax={nmax} is not divisible by the "
            f"{p}-device mesh — per-device partial folds need "
            "shard-aligned rows (prepare_spatial guarantees this)")
    return p


def init(state, cfg) -> ScanStats:
    """Fresh accumulators for one chunk (built INSIDE the jitted chunk
    program, so every chunk folds from zero and chunk packs merge by
    ``reduce_packs``)."""
    p = n_partials(cfg, int(state.ac.active.shape[-1]))
    nb = len(COUNT_BUCKETS) + 1
    z = jnp.zeros((), jnp.int32)
    zp = jnp.zeros((p,), jnp.int32)
    inf_p = jnp.full((p,), jnp.inf, jnp.float32)
    return ScanStats(
        steps=z, conf_peak=z, conf_sum=z,
        conf_hist=jnp.zeros((nb,), jnp.int32),
        los_peak=z, los_sum=z,
        los_hist=jnp.zeros((nb,), jnp.int32),
        engaged_peak=zp, occ_peak=zp, clamp_sat=zp, live_rowsteps=zp,
        min_sep_m=inf_p, headroom_min_m=inf_p)


def _dist_m(lat1, lon1, lat2, lon2):
    """Flat-earth (equirectangular) horizontal separation [m] — the
    deterministic cheap metric the fold uses everywhere (CD's own
    predicates stay authoritative for detection; this only ranks)."""
    coslat = jnp.cos(jnp.radians(0.5 * (lat1 + lat2)))
    dx = jnp.radians(lon2 - lon1) * coslat * _RE_M
    dy = jnp.radians(lat2 - lat1) * _RE_M
    return jnp.hypot(dx, dy)


def _partner_min_sep(ac, idx):
    """[N] per-row min separation to the listed partner rows (-1 =
    empty slot); +inf where nothing is engaged."""
    n = ac.lat.shape[0]
    j = jnp.clip(idx, 0, n - 1)
    valid = (idx >= 0) & ac.active[:, None] & ac.active[j]
    d = _dist_m(ac.lat[:, None], ac.lon[:, None], ac.lat[j], ac.lon[j])
    return jnp.min(jnp.where(valid, d, jnp.inf), axis=1)


def _min_sep(state, cfg, p: int):
    """[P] per-partial min separation among ENGAGED pairs (the pairs
    the resolver tracks — updated at CD cadence while positions move
    every step, so the fold captures the true closest approach between
    ASAS intervals).  Computed only single-device: partner gathers into
    a sharded row axis would lower to all-gathers, so any ``cd_mesh``
    reports +inf (docs/OBSERVABILITY.md catalogues the limitation)."""
    inf = jnp.full((p,), jnp.inf, jnp.float32)
    if cfg.cd_mesh is not None or not cfg.asas.swasas:
        return inf
    ac, asas = state.ac, state.asas
    if cfg.cd_backend == "dense":
        if asas.resopairs.size == 0:
            return inf
        mask = asas.resopairs & ac.active[:, None] & ac.active[None, :]
        d = _dist_m(ac.lat[:, None], ac.lon[:, None],
                    ac.lat[None, :], ac.lon[None, :])
        row = jnp.min(jnp.where(mask, d, jnp.inf), axis=1)
    elif cfg.cd_backend == "sparse":
        # sorted-space partner table -> caller rows (the SSD branch's
        # translation, shared via ops/cd_sched.partners_to_caller)
        from ..ops import cd_sched
        n = ac.lat.shape[0]
        n_tot = asas.partners_s.shape[0]
        ptable = cd_sched.partners_to_caller(
            asas.sort_perm, asas.partners_s, n, n_tot)
        row = _partner_min_sep(ac, ptable)
    else:                          # tiled / pallas: caller-space table
        if asas.partners.size == 0:
            return inf
        row = _partner_min_sep(ac, asas.partners)
    row = jnp.where(ac.active, row, jnp.inf)
    return jnp.min(row.reshape(p, -1), axis=1).astype(jnp.float32)


def fold(stats: ScanStats, state, cfg) -> ScanStats:
    """One step's fold (post-step state -> accumulators).  Pure
    reductions into the carry: no host syncs, no state writes, and no
    cross-device traffic beyond what the step itself already does."""
    from ..ops import aero
    p = stats.occ_peak.shape[0]
    ac, asas = state.ac, state.asas
    part = lambda x: x.reshape(p, -1)

    # --- replicated scalar folds (counts the CD kernels already reduce)
    nconf = asas.nconf_cur.astype(jnp.int32)
    nlos = asas.nlos_cur.astype(jnp.int32)
    bounds = jnp.asarray(COUNT_BUCKETS, jnp.float32)
    ci = jnp.searchsorted(bounds, nconf.astype(jnp.float32), side="left")
    li = jnp.searchsorted(bounds, nlos.astype(jnp.float32), side="left")

    # --- [P] per-partial folds (row split aligned with 'ac' shards)
    live = ac.active
    occ = jnp.sum(part(live), axis=1, dtype=jnp.int32)
    engaged = jnp.sum(part(asas.active & live), axis=1, dtype=jnp.int32)
    # envelope saturation: pilot targets are post-``perf.limits`` CLIPS,
    # so a binding envelope leaves the commanded CAS/alt ON the bound —
    # re-derive CAS from the arbitrated (allowed) TAS and compare
    cas_cmd = aero.vtas2cas(state.pilot.tas, state.pilot.alt)
    sat = live & ((cas_cmd <= state.perf.vmin + SAT_EPS_MS)
                  | (cas_cmd >= state.perf.vmax - SAT_EPS_MS)
                  | (state.pilot.alt >= state.perf.hmax - SAT_EPS_M))
    nsat = jnp.sum(part(sat), axis=1, dtype=jnp.int32)
    headroom = jnp.where(live, state.perf.hmax - ac.alt, jnp.inf)
    hr_min = jnp.min(part(headroom), axis=1).astype(jnp.float32)
    sep = _min_sep(state, cfg, p)

    return ScanStats(
        steps=stats.steps + 1,
        conf_peak=jnp.maximum(stats.conf_peak, nconf),
        conf_sum=stats.conf_sum + nconf,
        conf_hist=stats.conf_hist.at[ci].add(1),
        los_peak=jnp.maximum(stats.los_peak, nlos),
        los_sum=stats.los_sum + nlos,
        los_hist=stats.los_hist.at[li].add(1),
        engaged_peak=jnp.maximum(stats.engaged_peak, engaged),
        occ_peak=jnp.maximum(stats.occ_peak, occ),
        clamp_sat=stats.clamp_sat + nsat,
        live_rowsteps=stats.live_rowsteps + occ,
        min_sep_m=jnp.minimum(stats.min_sep_m, sep),
        headroom_min_m=jnp.minimum(stats.headroom_min_m, hr_min))


# ------------------------------------------------------------------ host side

def reduce_packs(packs):
    """Merge host-side chunk packs into one: sums add, peaks max, mins
    min — the edge-side reduction of the per-device/per-chunk partials,
    and the oracle's 'twenty 1-step chunks == one 20-step chunk'."""
    packs = list(packs)
    if not packs:
        raise ValueError("reduce_packs: need at least one pack")
    out = {}
    for f in SUM_FIELDS:
        out[f] = np.sum([np.asarray(getattr(q, f)) for q in packs],
                        axis=0)
    for f in MAX_FIELDS:
        out[f] = np.max([np.asarray(getattr(q, f)) for q in packs],
                        axis=0)
    for f in MIN_FIELDS:
        out[f] = np.min([np.asarray(getattr(q, f)) for q in packs],
                        axis=0)
    return ScanStats(**out)


def summarize(pack) -> dict:
    """Edge-side reduction of one host pack to the HEALTH/heartbeat
    summary: partials collapse here (sum/max/min over [P]), non-finite
    mins map to None so the dict stays JSON/msgpack-clean."""
    steps = int(np.asarray(pack.steps))
    live = int(np.sum(np.asarray(pack.live_rowsteps)))
    sat = int(np.sum(np.asarray(pack.clamp_sat)))
    occ = np.asarray(pack.occ_peak)
    min_sep = float(np.min(np.asarray(pack.min_sep_m)))
    headroom = float(np.min(np.asarray(pack.headroom_min_m)))
    return {
        "steps": steps,
        "conf_peak": int(np.asarray(pack.conf_peak)),
        "conf_mean": round(float(np.asarray(pack.conf_sum))
                           / max(steps, 1), 3),
        "los_peak": int(np.asarray(pack.los_peak)),
        # sum of per-partial peaks: exact single-device, an upper bound
        # on the fleet-wide instantaneous peak under spatial stripes
        "engaged_peak": int(np.sum(np.asarray(pack.engaged_peak))),
        "occ_peak": int(np.max(occ)) if occ.size else 0,
        "occ_imbalance": round(float(np.max(occ))
                               / max(float(np.mean(occ)), 1e-9), 3)
        if occ.size > 1 and float(np.mean(occ)) > 0 else 1.0,
        "clamp_sat_ratio": round(sat / live, 6) if live else 0.0,
        "min_sep_m": round(min_sep, 1) if np.isfinite(min_sep) else None,
        "alt_headroom_min_m": round(headroom, 1)
        if np.isfinite(headroom) else None,
    }


def merge_summaries(summaries):
    """Worst-case merge of ``summarize`` dicts across worlds/workers
    (the heartbeat + fleet-HEALTH reduction): steps add, peaks and
    alert ratios take the worst offender, minima take the closest
    call; the mean re-weights by steps so busy chunks dominate."""
    summaries = [s for s in summaries if s]
    if not summaries:
        return None
    steps = sum(int(s.get("steps", 0)) for s in summaries)
    wmean = (sum(float(s.get("conf_mean", 0.0))
                 * int(s.get("steps", 0)) for s in summaries)
             / steps) if steps else 0.0

    def _max(key):
        return max((s.get(key) or 0) for s in summaries)

    def _min(key):
        vals = [s[key] for s in summaries if s.get(key) is not None]
        return min(vals) if vals else None

    return {
        "steps": steps, "conf_peak": _max("conf_peak"),
        "conf_mean": round(wmean, 3), "los_peak": _max("los_peak"),
        "engaged_peak": _max("engaged_peak"),
        "occ_peak": _max("occ_peak"),
        "occ_imbalance": _max("occ_imbalance"),
        "clamp_sat_ratio": _max("clamp_sat_ratio"),
        "min_sep_m": _min("min_sep_m"),
        "alt_headroom_min_m": _min("alt_headroom_min_m"),
    }


#: Registry series the drain feeds (docs/OBSERVABILITY.md catalogue).
#: Counters/histograms ship fleet-wide through the existing heartbeat
#: ``Registry.delta()`` path and add exactly; gauges are last-chunk.
SERIES_HELP = {
    "sim_scan_conf_per_step": "per-step conflict count (in-scan fold)",
    "sim_scan_los_per_step": "per-step LoS count (in-scan fold)",
    "sim_scan_steps": "steps folded by in-scan telemetry",
    "sim_scan_clamp_sat_rowsteps":
        "live row-steps with a binding perf envelope clamp",
    "sim_scan_live_rowsteps": "live row-steps folded (ratio denominator)",
    "sim_scan_conf_peak": "last chunk's peak per-step conflict count",
    "sim_scan_los_peak": "last chunk's peak per-step LoS count",
    "sim_scan_engaged_peak": "last chunk's peak resolver-engaged rows",
    "sim_scan_occupancy_peak": "last chunk's peak per-stripe occupancy",
    "sim_scan_min_sep_m": "last chunk's min engaged-pair separation [m]",
    "sim_scan_alt_headroom_min_m":
        "last chunk's min live-row ceiling headroom [m]",
    "sim_scan_clamp_sat_ratio":
        "last chunk's clamp-saturated fraction of live row-steps",
}


def drain(reg, pack) -> dict:
    """Fold one chunk's host pack into a metrics Registry: histogram
    bucket counts merge count-exactly (``Histogram.add_counts``),
    totals ride counters (fleet-mergeable), last-chunk reductions land
    in gauges.  Returns the ``summarize`` dict (HEALTH / heartbeat)."""
    s = summarize(pack)
    if s["steps"] == 0:
        return s
    hlp = SERIES_HELP
    reg.histogram("sim_scan_conf_per_step", buckets=COUNT_BUCKETS,
                  help=hlp["sim_scan_conf_per_step"]).add_counts(
        np.asarray(pack.conf_hist).tolist(),
        float(np.asarray(pack.conf_sum)))
    reg.histogram("sim_scan_los_per_step", buckets=COUNT_BUCKETS,
                  help=hlp["sim_scan_los_per_step"]).add_counts(
        np.asarray(pack.los_hist).tolist(),
        float(np.asarray(pack.los_sum)))
    reg.counter("sim_scan_steps", help=hlp["sim_scan_steps"]).inc(
        s["steps"])
    reg.counter("sim_scan_clamp_sat_rowsteps",
                help=hlp["sim_scan_clamp_sat_rowsteps"]).inc(
        int(np.sum(np.asarray(pack.clamp_sat))))
    reg.counter("sim_scan_live_rowsteps",
                help=hlp["sim_scan_live_rowsteps"]).inc(
        int(np.sum(np.asarray(pack.live_rowsteps))))
    g = lambda name, v: reg.gauge(name, help=hlp[name]).set(v)
    g("sim_scan_conf_peak", s["conf_peak"])
    g("sim_scan_los_peak", s["los_peak"])
    g("sim_scan_engaged_peak", s["engaged_peak"])
    g("sim_scan_occupancy_peak", s["occ_peak"])
    g("sim_scan_clamp_sat_ratio", s["clamp_sat_ratio"])
    if s["min_sep_m"] is not None:
        g("sim_scan_min_sep_m", s["min_sep_m"])
    if s["alt_headroom_min_m"] is not None:
        g("sim_scan_alt_headroom_min_m", s["alt_headroom_min_m"])
    return s
