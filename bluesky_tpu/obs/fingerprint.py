"""Device-side state fingerprints: a bit-pattern fold for SDC defense.

Silent data corruption — a flipped HBM bit, a marginal ALU, a corrupted
completion payload — completes a piece *wrong* without tripping the
in-scan isfinite guard (a flipped mantissa bit in ``lat`` is still
finite).  The defense (docs/FAULT_TOLERANCE.md §SDC defense) is
comparison: two executions of the same piece on healthy workers produce
the same stepped state bit-for-bit, so a cheap order-sensitive fold of
the state's raw bit patterns is a complete-state witness the server can
compare across hedge duplicates, shadow audits and 2-of-3 votes.

``FingerprintPack`` rides the chunk-scan CARRY exactly like ScanStats
(obs/scanstats.py): folded once per step from the post-step state,
emitted once per chunk as an extra non-donated output next to the
telemetry pack, behind the jit-static ``SimConfig.fingerprint`` flag.

Contracts (tests/test_sdc.py, the obs_smoke parity hash):

* **Off path is free.**  With the flag False the chunk scan traces the
  exact pre-existing HLO; folding never writes state, so the stepped
  state is bit-identical either way.
* **Zero host syncs, zero in-scan collectives.**  The fold is pure
  bitwise arithmetic on the carry; per-aircraft words fold to ``[P]``
  PER-DEVICE PARTIALS via the same ``reshape(P, nmax // P)`` row split
  as ScanStats (GSPMD keeps it local), XOR-combined host-side at the
  chunk edge.
* **Deterministic and order-sensitive.**  XOR alone would miss a value
  swapped between steps or fields; each step's contribution rotates the
  running fold left by one bit, and each guarded field's word is
  rotated by its field index, so time- and field-transposed corruption
  changes the fingerprint.  Comparability across workers assumes the
  deployment invariant the serving layer already holds: the same piece
  dispatched with the same SimConfig and the same nmax bucket (content-
  addressed pieces + the pack compatibility key guarantee this).

The fold watches the ``GUARD_FIELDS`` kinematic outputs plus the live
mask — the same complete-coverage argument as the isfinite guard: any
upstream corruption reaches one of these within a step or two, and a
fold over six [N] f32 columns stays ≪1% of the step pipeline.
"""
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .scanstats import n_partials

#: 32-bit mask for host-side chain arithmetic (Python ints are wide).
_M32 = 0xFFFFFFFF


class FingerprintPack(NamedTuple):
    """Per-chunk fingerprint accumulator (the scan-carry resident).

    ``fp`` keeps [P] per-device partial folds (P = mesh size when a
    device mesh divides nmax, else 1 — ``scanstats.n_partials``), XORed
    into one 32-bit word host-side; ``steps`` counts folds so the host
    can sanity-check chunk arity when comparing.
    """
    fp: jnp.ndarray      # [P] uint32 — per-device partial folds
    steps: jnp.ndarray   # [] int32 — steps folded


def _rotl(x, k: int):
    """Rotate a uint32 word left by a static k (bits)."""
    k %= 32
    if k == 0:
        return x
    return (x << k) | (x >> (32 - k))


def _words(x) -> jnp.ndarray:
    """Bitcast any state leaf to uint32 words, shape-preserving: bools
    widen, 64-bit leaves XOR their two words (x64 mode safe)."""
    x = jnp.asarray(x)
    if x.dtype == jnp.bool_:
        return x.astype(jnp.uint32)
    if jnp.issubdtype(x.dtype, jnp.integer) and x.dtype.itemsize <= 4:
        return x.astype(jnp.uint32)
    v = jax.lax.bitcast_convert_type(x, jnp.uint32)
    if v.ndim > x.ndim:          # 64-bit leaf split into 2 words
        v = v[..., 0] ^ v[..., 1]
    return v


def init(state, cfg) -> FingerprintPack:
    """Fresh fold for one chunk (built INSIDE the jitted chunk program,
    so chunk packs chain host-side from a known zero)."""
    p = n_partials(cfg, int(state.ac.active.shape[-1]))
    return FingerprintPack(fp=jnp.zeros((p,), jnp.uint32),
                           steps=jnp.zeros((), jnp.int32))


def fold(pack: FingerprintPack, state, cfg) -> FingerprintPack:
    """One scan-body fold of the post-step state into the carry.

    ``fp' = rotl(fp, 1) XOR step_word`` where ``step_word[P]`` XORs the
    row split of every watched column, each column pre-rotated by its
    field index.  Pure bitwise ops — no reductions beyond the row XOR,
    which GSPMD keeps shard-local (rows align with 'ac' shards).
    """
    from ..core.step import GUARD_FIELDS
    p = pack.fp.shape[0]
    ac = state.ac
    acc = _words(ac.active).reshape(p, -1)
    for i, f in enumerate(GUARD_FIELDS):
        acc = acc ^ _rotl(_words(getattr(ac, f)).reshape(p, -1), i + 1)
    part = jnp.bitwise_xor.reduce(acc, axis=1)        # [P], shard-local
    return FingerprintPack(fp=_rotl(pack.fp, 1) ^ part,
                           steps=pack.steps + 1)


# ------------------------------------------------------------------ host side

def combine(pack) -> int:
    """XOR a (device_get) pack's [P] partials into one 32-bit int."""
    fp = np.asarray(pack.fp, dtype=np.uint64)
    return int(np.bitwise_xor.reduce(fp)) & _M32 if fp.size else 0


def chain(prev: int, chunk_fp: int) -> int:
    """Fold one chunk fingerprint into the running piece chain — the
    same rotate-XOR recurrence as the in-scan fold, so chunk order
    matters and re-chunked identical runs still disagree only when the
    stepped states disagree."""
    prev &= _M32
    return (((prev << 1) | (prev >> 31)) ^ chunk_fp) & _M32


def summarize(chain_fp: int, chunks: int, steps: int) -> dict:
    """The wire/heartbeat summary dict for a running piece chain."""
    return {"fp": format(chain_fp & _M32, "08x"),
            "chunks": int(chunks), "steps": int(steps)}


def drain(reg, pack) -> int:
    """Retire one chunk pack into a metrics registry: returns the
    combined 32-bit chunk fingerprint and counts the fold cadence."""
    fp = combine(pack)
    reg.counter("sim_fp_chunks",
                "Chunks retired with a state fingerprint fold").inc()
    reg.counter("sim_fp_steps",
                "Steps folded into state fingerprints").inc(
                    int(np.asarray(pack.steps)))
    return fp
