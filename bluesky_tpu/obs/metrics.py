"""Metrics registry: counters, gauges, fixed-bucket histograms.

Dependency-free (stdlib only) so every layer — core step wrappers,
sim, worker node, broker — can register series without import cycles.
Three rules keep it cheap and mergeable:

* **Fixed buckets.**  Histograms are classic Prometheus-style
  cumulative-bucket-free arrays: per-bucket hit counts against a fixed
  upper-bound ladder, plus running sum/count.  Observing is one
  ``bisect`` + two adds; percentiles are linear interpolation inside
  the owning bucket, which is all a fleet aggregate can honestly
  promise anyway.

* **Delta shipping.**  ``Registry.delta()`` returns the increments
  since the previous ``delta()`` call (counters and histogram arrays
  subtract; gauges ship their level).  Worker heartbeats piggyback
  that dict upstream, and the server's fleet registry ``merge()``s it
  — sums of deltas commute, so out-of-order heartbeats from W workers
  still aggregate exactly.

* **Atomic export.**  ``maybe_export()`` rewrites a Prometheus text
  file via tmp+``os.replace`` at most once per interval, so a scraper
  never reads a torn file.
"""
import bisect
import os
import threading
import time

# Wall-clock-ms ladder shared by the latency histograms: sub-ms device
# polls up through multi-second compile/restore stalls.
DEFAULT_MS_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0,
                      50.0, 100.0, 250.0, 500.0, 1000.0, 2500.0,
                      5000.0, 10000.0)
# Seconds ladder for queue-wait style series (admission → dispatch).
DEFAULT_S_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0,
                     2.5, 5.0, 10.0, 30.0, 60.0, 300.0)


class Counter:
    """Monotonic float counter (``inc`` only; ``_set`` exists for the
    pipe_stats compatibility view and delta merging)."""
    __slots__ = ("name", "help", "_value")

    def __init__(self, name, help=""):
        self.name = name
        self.help = help
        self._value = 0.0

    def inc(self, n=1.0):
        self._value += n

    def _set(self, v):
        self._value = float(v)

    @property
    def value(self):
        return self._value


class Gauge:
    """Last-written level (queue depth, ring occupancy, ...)."""
    __slots__ = ("name", "help", "_value")

    def __init__(self, name, help=""):
        self.name = name
        self.help = help
        self._value = 0.0

    def set(self, v):
        self._value = float(v)

    def inc(self, n=1.0):
        self._value += n

    @property
    def value(self):
        return self._value


class Histogram:
    """Fixed-upper-bound buckets + overflow, with running sum/count."""
    __slots__ = ("name", "help", "bounds", "counts", "sum", "count")

    def __init__(self, name, buckets=DEFAULT_MS_BUCKETS, help=""):
        self.name = name
        self.help = help
        self.bounds = tuple(float(b) for b in buckets)
        if list(self.bounds) != sorted(self.bounds):
            raise ValueError(f"{name}: bucket bounds must be sorted")
        self.counts = [0] * (len(self.bounds) + 1)   # last = overflow
        self.sum = 0.0
        self.count = 0

    def observe(self, v):
        v = float(v)
        self.counts[bisect.bisect_left(self.bounds, v)] += 1
        self.sum += v
        self.count += 1

    def add_counts(self, counts, sum=0.0):
        """Fold a pre-bucketed count vector (same ladder + overflow
        layout) into this histogram — the scanstats drain path, where
        the device already histogrammed per-step values with
        ``searchsorted(side='left')`` (the exact ``bisect_left`` rule
        ``observe`` uses), so bucket counts merge count-exactly."""
        if len(counts) != len(self.counts):
            raise ValueError(
                f"{self.name}: add_counts got {len(counts)} buckets, "
                f"ladder has {len(self.counts)}")
        n = 0
        for i, c in enumerate(counts):
            c = int(c)
            self.counts[i] += c
            n += c
        self.sum += float(sum)
        self.count += n

    @property
    def mean(self):
        return self.sum / self.count if self.count else 0.0

    def percentile(self, p):
        """Estimate the p-quantile (p in [0,1]) by linear interpolation
        inside the owning bucket; the overflow bucket reports its lower
        bound (the best honest answer for an unbounded tail)."""
        if not self.count:
            return 0.0
        rank = p * self.count
        cum = 0
        for i, c in enumerate(self.counts):
            if not c:
                continue
            if cum + c >= rank:
                lo = self.bounds[i - 1] if i > 0 else 0.0
                if i >= len(self.bounds):          # overflow bucket
                    return self.bounds[-1]
                hi = self.bounds[i]
                frac = (rank - cum) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            cum += c
        return self.bounds[-1]


class Registry:
    """Named metrics with get-or-create accessors, delta shipping and
    Prometheus/human text export.  One per component (each Simulation,
    the broker, the broker's fleet aggregate) — NOT process-global, so
    co-located components (tests run server+worker in one process, a
    WorldBatch runs W sims) never mix series."""

    def __init__(self):
        self._metrics = {}           # name -> Counter/Gauge/Histogram
        # reentrant: merge()/delta() hold it across get-or-create calls
        self._lock = threading.RLock()
        self._delta_base = {}        # name -> shipped-so-far baseline
        self._last_export = 0.0

    # ------------------------------------------------------------ access
    def counter(self, name, help=""):
        return self._get_or_make(name, Counter, help=help)

    def gauge(self, name, help=""):
        return self._get_or_make(name, Gauge, help=help)

    def histogram(self, name, buckets=DEFAULT_MS_BUCKETS, help=""):
        return self._get_or_make(name, Histogram, buckets=buckets,
                                 help=help)

    def get(self, name):
        return self._metrics.get(name)

    def _get_or_make(self, name, cls, **kw):
        m = self._metrics.get(name)
        if m is None:
            with self._lock:
                m = self._metrics.get(name)
                if m is None:
                    m = cls(name, **kw)
                    self._metrics[name] = m
        if not isinstance(m, cls):
            raise TypeError(f"{name} already registered as "
                            f"{type(m).__name__}, not {cls.__name__}")
        return m

    def __iter__(self):
        return iter(list(self._metrics.values()))

    def __len__(self):
        return len(self._metrics)

    # ---------------------------------------------------------- snapshot
    def snapshot(self):
        """Plain-dict view of every metric (msgpack/JSON-safe)."""
        out = {}
        for m in self:
            if isinstance(m, Counter):
                out[m.name] = {"type": "counter", "value": m.value}
            elif isinstance(m, Gauge):
                out[m.name] = {"type": "gauge", "value": m.value}
            else:
                out[m.name] = {"type": "histogram",
                               "bounds": list(m.bounds),
                               "counts": list(m.counts),
                               "sum": m.sum, "count": m.count,
                               "p50": m.percentile(0.5),
                               "p95": m.percentile(0.95)}
        return out

    def delta(self):
        """Increments since the previous ``delta()`` call — the payload
        worker heartbeats ship upstream.  Counters/histograms subtract
        against the shipped baseline; gauges ship their current level.
        Zero-change series are omitted so an idle worker's heartbeat
        stays small."""
        with self._lock:
            out = {}
            for m in self:
                if isinstance(m, Counter):
                    base = self._delta_base.get(m.name, 0.0)
                    d = m.value - base
                    if d:
                        out[m.name] = {"type": "counter", "value": d}
                        self._delta_base[m.name] = m.value
                elif isinstance(m, Gauge):
                    out[m.name] = {"type": "gauge", "value": m.value}
                else:
                    base = self._delta_base.get(m.name)
                    if base is None:
                        base = {"counts": [0] * len(m.counts),
                                "sum": 0.0, "count": 0}
                    dcount = m.count - base["count"]
                    if dcount:
                        out[m.name] = {
                            "type": "histogram",
                            "bounds": list(m.bounds),
                            "counts": [a - b for a, b in
                                       zip(m.counts, base["counts"])],
                            "sum": m.sum - base["sum"],
                            "count": dcount}
                        self._delta_base[m.name] = {
                            "counts": list(m.counts),
                            "sum": m.sum, "count": m.count}
            return out

    def merge(self, delta):
        """Fold a ``delta()``/``snapshot()`` dict into this registry
        (the server's fleet aggregate).  Counter/histogram increments
        add — sums of deltas commute, so interleaved heartbeats from
        many workers aggregate exactly; gauges are last-writer."""
        if not delta:
            return
        with self._lock:
            for name, d in delta.items():
                t = d.get("type")
                if t == "counter":
                    self.counter(name).inc(float(d.get("value", 0.0)))
                elif t == "gauge":
                    self.gauge(name).set(float(d.get("value", 0.0)))
                elif t == "histogram":
                    h = self.histogram(name,
                                       buckets=d.get("bounds",
                                                     DEFAULT_MS_BUCKETS))
                    counts = d.get("counts", [])
                    if len(counts) == len(h.counts):
                        for i, c in enumerate(counts):
                            h.counts[i] += int(c)
                    h.sum += float(d.get("sum", 0.0))
                    h.count += int(d.get("count", 0))

    # ------------------------------------------------------------ export
    def prometheus_text(self):
        """Prometheus exposition-format dump (text/plain version 0.0.4,
        cumulative ``le`` buckets).  Series are emitted in sorted-name
        order — NOT registry insertion order, which varies with the
        code path that first touched each series (lazily-registered
        series like the scanstats drain would otherwise reshuffle the
        file between scrapes) — so consecutive ``export()`` files diff
        cleanly (tests/test_obs.py pins the ordering)."""
        lines = []
        for m in sorted(self, key=lambda m: m.name):
            if isinstance(m, Counter):
                lines.append(f"# TYPE {m.name} counter")
                lines.append(f"{m.name} {m.value:g}")
            elif isinstance(m, Gauge):
                lines.append(f"# TYPE {m.name} gauge")
                lines.append(f"{m.name} {m.value:g}")
            else:
                lines.append(f"# TYPE {m.name} histogram")
                cum = 0
                for b, c in zip(m.bounds, m.counts):
                    cum += c
                    lines.append(f'{m.name}_bucket{{le="{b:g}"}} {cum}')
                lines.append(f'{m.name}_bucket{{le="+Inf"}} {m.count}')
                lines.append(f"{m.name}_sum {m.sum:g}")
                lines.append(f"{m.name}_count {m.count}")
        return "\n".join(lines) + "\n"

    def text(self):
        """Human console dump (the METRICS DUMP echo)."""
        lines = []
        for m in sorted(self, key=lambda m: m.name):
            if isinstance(m, Counter):
                lines.append(f"{m.name}: {m.value:g}")
            elif isinstance(m, Gauge):
                lines.append(f"{m.name}: {m.value:g} (gauge)")
            elif m.count:
                lines.append(
                    f"{m.name}: n={m.count} mean={m.mean:.3g} "
                    f"p50={m.percentile(0.5):.3g} "
                    f"p95={m.percentile(0.95):.3g}")
            else:
                lines.append(f"{m.name}: n=0")
        return "\n".join(lines) if lines else "(no metrics registered)"

    def export(self, path):
        """Atomic Prometheus-text rewrite: tmp + ``os.replace`` so a
        concurrent scraper never reads a torn file."""
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(self.prometheus_text())
        os.replace(tmp, path)
        return path

    def maybe_export(self, path=None, interval=None, now=None):
        """Rate-limited ``export()`` driven by the settings knobs —
        called from the sim's after-chunk hook / the server poll loop,
        so no extra thread is needed."""
        if path is None or interval is None:
            from .. import settings
            path = path if path is not None else getattr(
                settings, "metrics_export_path", "")
            interval = interval if interval is not None else float(
                getattr(settings, "metrics_export_dt", 10.0))
        if not path:
            return None
        now = time.monotonic() if now is None else now
        if now - self._last_export < max(float(interval), 0.0):
            return None
        self._last_export = now
        try:
            return self.export(path)
        except OSError:
            return None            # a bad export path never kills a run


_DEFAULT = Registry()


def get_registry():
    """The process-default registry — for code with no owning component
    (scripts, ad-hoc probes).  Sim/server code uses its own instance."""
    return _DEFAULT
