"""Legacy BlueSky (BS) performance coefficient database.

Parses the reference's conceptual-design XML files
(``data/performance/BS/{aircraft,engines}``) into per-type dicts, with
operation-for-operation parity to the reference ``CoeffBS``
(``traffic/performance/legacy/coeff_bs.py:31-363``): unit conversion
table, derived takeoff/landing minimum speeds (CS-25.107 factors or
clmax fallback), Raymer parasite drag from Cfe*Swet/Sref, Obert/Nita
induced-drag fallback for missing Oswald factors, ADS-B-statistical
ground accelerations, and the BPR-category SFC table for jet engines.

Structure divergence: per-type dicts (merged aircraft+engine view per
first-listed available engine) instead of 30 parallel lists — the slot
filler writes columns from one dict lookup.
"""
import os
from math import pi, sqrt
from xml.etree import ElementTree
from typing import Dict, Optional

from ..ops import aero

# Unit conversion factors (coeff_bs.py:34-52)
_FACTORS = {
    "kg": 1.0, "t": 1000.0, "lbs": aero.lbs, "N": 1.0, "W": 1.0,
    "m": 1.0, "km": 1000.0, "inch": aero.inch, "ft": aero.ft,
    "sqm": 1.0, "sqft": aero.sqft, "sqin": 0.0254 * 0.0254,
    "m/s": 1.0, "km/h": 1.0 / 3.6, "kts": aero.kts, "fpm": aero.fpm,
    "kg/s": 1.0, "kg/m": 1.0 / 60.0, "mug/J": 1e-6, "mg/J": 1e-3,
    "kW": 1000.0, "kN": 1000.0, "": 1.0,
}

# Phase-dependent drag scaling, order TO/IC/CR/AP/LD/LD-gear
# (FAA 2005 SAGE; coeff_bs.py:98-102)
D_CD0_JET = [1.476, 1.143, 1.0, 1.957, 3.601, 1.037]
D_K_JET = [1.01, 1.071, 1.0, 0.992, 0.932, 1.0]
D_CD0_TP = [1.220, 1.0, 1.0, 1.279, 1.828, 0.496]
D_K_TP = [0.948, 1.0, 1.0, 0.94, 0.916, 1.0]

# Jet SFC by bypass-ratio category (Raymer p.36; coeff_bs.py:306-309)
SFC_BY_BPR_CAT = [14.1, 22.7, 25.5]


def _convert(node):
    unit = node.attrib.get("unit", "")
    return _FACTORS.get(unit, 1.0) * float(node.text)


def load_engines(path: str) -> Dict[str, dict]:
    """engines/*.xml -> {name: engine dict} (coeff_bs.py:291-330)."""
    out = {}
    for fname in sorted(os.listdir(path)):
        if not fname.endswith(".xml"):
            continue
        doc = ElementTree.parse(os.path.join(path, fname))
        name = doc.find("engines/engine").text
        etype = int(doc.find("engines/eng_type").text)
        d = dict(name=name, eng_type=etype)
        if etype == 1:      # jet
            d["thr"] = _convert(doc.find("engines/Thr"))
            d["bpr_cat"] = int(doc.find("engines/BPR_cat").text)
            d["sfc"] = SFC_BY_BPR_CAT[d["bpr_cat"]]
            for ff in ("ff_to", "ff_cl", "ff_cr", "ff_ap", "ff_id"):
                d[ff] = _convert(doc.find(f"ff/{ff}"))
        elif etype == 2:    # turboprop
            d["power"] = _convert(doc.find("engines/Power"))
            psfc_to = _convert(doc.find("SFC/SFC_TO"))
            d["psfc_to"] = psfc_to
            # Babikian cruise-PSFC fit (coeff_bs.py:327-329)
            d["psfc_cr"] = (0.7675 * psfc_to * 1e6 + 23.576) * 1e-6
        out[name] = d
    return out


def load_aircraft_file(fname: str) -> Optional[dict]:
    """One aircraft XML -> coefficient dict (coeff_bs.py:112-271)."""
    doc = ElementTree.parse(fname)
    d = {}
    d["actype"] = doc.find("ac_type").text
    etype = int(doc.find("engine/eng_type").text)
    d["eng_type"] = etype
    d["n_eng"] = float(doc.find("engine/num_eng").text)
    d["engines"] = [e.text for e in doc.findall("engine/eng")]

    mtow = _convert(doc.find("weights/MTOW"))
    mlw = _convert(doc.find("weights/MLW"))
    d["mtow"] = mtow
    span = _convert(doc.find("dimensions/span"))
    sref = _convert(doc.find("dimensions/wing_area"))
    swet = _convert(doc.find("dimensions/wetted_area"))
    d["sref"] = sref

    crma = float(doc.find("speeds/cr_MA").text)
    d["cr_mach"] = crma if crma != 0.0 else 0.8
    crspd = doc.find("speeds/cr_spd")
    d["cr_spd"] = _convert(crspd) if float(crspd.text) != 0.0 \
        else 250.0 * aero.kts

    # Ground accel/decel by engine type / engine count (coeff_bs.py:171-190)
    if etype == 2:
        d["gr_acc"], d["gr_dec"] = 2.12, 1.12
    elif d["n_eng"] == 2.0:
        d["gr_acc"], d["gr_dec"] = 1.94, 1.265
    else:
        d["gr_acc"], d["gr_dec"] = 1.68, 1.131

    # Minimum takeoff speed (coeff_bs.py:194-201)
    tospd = doc.find("speeds/to_spd")
    if float(tospd.text) == 0.0:
        clmax_to = float(doc.find("aerodynamics/clmax_to").text)
        d["vmto"] = sqrt((2.0 * aero.g0) / (sref * clmax_to))
    else:
        d["vmto"] = _convert(tospd) / (1.13 * sqrt(mtow / aero.rho0))
    d["clmax_cr"] = float(doc.find("aerodynamics/clmax_cr").text)

    # Minimum landing speed (coeff_bs.py:207-214)
    ldspd = doc.find("speeds/ld_spd")
    if float(ldspd.text) == 0.0:
        clmax_ld = float(doc.find("aerodynamics/clmax_ld").text)
        d["vmld"] = sqrt((2.0 * aero.g0) / (sref * clmax_ld))
    else:
        d["vmld"] = _convert(ldspd) / (1.23 * sqrt(mlw / aero.rho0))

    maxspd = doc.find("limits/max_spd")
    d["max_spd"] = _convert(maxspd) if float(maxspd.text) != 0.0 else 400.0
    maxma = doc.find("limits/max_MA")
    d["max_mach"] = float(maxma.text) if float(maxma.text) != 0.0 else 0.8
    maxalt = doc.find("limits/max_alt")
    d["max_alt"] = _convert(maxalt) if float(maxalt.text) != 0.0 \
        else 11000.0

    # Parasite drag (Raymer p.429) + induced drag (coeff_bs.py:241-251)
    cfe = float(doc.find("aerodynamics/Cfe").text)
    d["cd0"] = cfe * swet / sref
    oswald = float(doc.find("aerodynamics/oswald").text)
    ar = span * span / sref
    if oswald == 0.0:
        # Obert 2009 p.542 / Nita 2012 fallback
        d["k"] = 1.02 / (pi * ar) + 0.009
    else:
        d["k"] = 1.0 / (pi * oswald * ar)
    return d


def bs_to_generic(d: dict) -> dict:
    """Map a BS coefficient dict onto the generic PerfArrays column keys
    (the OpenAP-shaped slot schema in models/perf_coeffs.py).

    This gives the scanned step real per-type legacy data (mass, wing,
    thrust, drag polar with the SAGE phase scalings baked into the
    per-phase cd0 columns, fuel flows, envelope); the *full* legacy
    physics (ESF thrust/fuel regimes) lives in ops/perf_legacy.py /
    ops/perf_bada.py as golden-tested kernels.  Approximations are
    explicit below.
    """
    import math
    eng = d.get("engine", {})
    etype = d.get("eng_type", 1)
    scale = D_CD0_JET if etype == 1 else D_CD0_TP
    cd0 = d["cd0"]
    if etype == 1:
        engthr = eng.get("thr", 120000.0)
        ffs = dict(ff_idl=eng.get("ff_id", 0.1),
                   ff_app=eng.get("ff_ap", 0.3),
                   ff_co=eng.get("ff_cl", 0.9), ff_to=eng.get("ff_to", 1.2))
    else:
        # Turboprop: power-to-thrust at the Raymer propeller efficiency
        # and a representative 75 m/s climb-out speed (approximation —
        # the reference models TP thrust via power/speed continuously)
        power = eng.get("power", 2e6)
        engthr = 0.8 * power / 75.0
        psfc = eng.get("psfc_to", 0.7e-6)
        ffs = dict(ff_idl=psfc * power * 0.1, ff_app=psfc * power * 0.3,
                   ff_co=psfc * power * 0.85, ff_to=psfc * power)
    # Legacy vmto/vmld are CS-25 coefficients multiplied by
    # sqrt(mass/rho) at runtime; evaluated at MTOW, sea-level ISA here.
    sqmr = math.sqrt(d["mtow"] / aero.rho0)
    vminto = d["vmto"] * sqmr
    vminld = d["vmld"] * sqmr
    # Minimum clean-config speed from clmax_cr at MTOW/SL
    vmincr = math.sqrt(2.0 * d["mtow"] * aero.g0
                       / (aero.rho0 * d["clmax_cr"] * d["sref"]))
    return dict(
        # slot mass = 0.5*(oew+mtow); the legacy model flies at MTOW
        # (perfbs.py:128), so oew is set to mtow to reproduce that
        n_engines=int(d["n_eng"]), wa=d["sref"],
        mtow=d["mtow"], oew=d["mtow"],
        engthr=engthr, engbpr=6.0 if etype == 1 else 0.0,
        cd0_clean=cd0 * scale[2], cd0_gd=cd0 * scale[5],
        cd0_to=cd0 * scale[0], cd0_ic=cd0 * scale[1],
        cd0_ap=cd0 * scale[3], cd0_ld=cd0 * scale[4],
        k=d["k"],
        vminto=vminto, vmaxto=vminto * 1.4,
        vminic=vminto * 1.1, vmaxic=vminto * 1.5,
        vminer=vmincr, vmaxer=d["max_spd"],
        vminap=vminld * 1.1, vmaxap=vminld * 1.8,
        vminld=vminld, vmaxld=vminld * 1.5,
        vsmin=-3000.0 * aero.fpm, vsmax=2500.0 * aero.fpm,
        hmax=d["max_alt"], axmax=d["gr_acc"],
        **ffs)


def load_bs_dir(path: str) -> Dict[str, dict]:
    """Parse a BS-layout directory: {actype: merged aircraft+engine dict}.

    The first engine listed in the aircraft file that exists in the
    engine database is merged in (coeff_bs.py:258-262 "first engine is
    taken!").  Returns {} if the directory is missing.
    """
    acdir = os.path.join(path, "aircraft")
    endir = os.path.join(path, "engines")
    if not os.path.isdir(acdir) or not os.path.isdir(endir):
        return {}
    engines = load_engines(endir)
    out = {}
    for fname in sorted(os.listdir(acdir)):
        if not fname.endswith(".xml"):
            continue
        try:
            d = load_aircraft_file(os.path.join(acdir, fname))
        except (ElementTree.ParseError, AttributeError, ValueError):
            continue
        if d is None:
            continue
        eng = next((engines[e] for e in d["engines"] if e in engines),
                   None)
        if eng is not None:
            d["engine"] = eng
        out[d["actype"].upper()] = d
    return out
