"""BADA 3 coefficient loader: SYNONYM.NEW + per-type OPF/APF files.

Parity with the reference ``traffic/performance/bada/coeff_bada.py:70-230``
(EEC Technical Report 14/04/24-44 file layout): the synonym table maps
ICAO type codes to coefficient files; each ``.OPF`` carries the mass,
envelope, aerodynamics, thrust, fuel and ground blocks; the optional
``.APF`` carries low/avg/high reference speed profiles.  BADA data is
proprietary and NOT shipped — ``load_bada_dir`` returns {} when the
directory has no SYNONYM.NEW, and everything here is exercised in tests
against synthetic files written in the exact BADA fixed-width format.

Structure divergence: coefficients land in plain per-type dicts (the
slot filler's common currency) instead of ACData attribute objects.
"""
import os
import re
from glob import glob
from typing import Dict, Tuple

from .fwparser import FixedWidthParser, ParseError

SYN_FORMAT = ["CD, 1X, 1S, 1X, 4S, 3X, 18S, 1X, 25S, 1X, 6S, 2X, 1S"]

OPF_FORMAT = [
    # aircraft type block (1 data line)
    "CD, 3X, 6S, 9X, 1I, 12X, 9S, 17X, 1S",
    # mass block (1 data line)
    "CD, 2X, 3X, 10F, 3X, 10F, 3X, 10F, 3X, 10F, 3X, 10F",
    # flight envelope block (1 data line)
    "CD, 2X, 3X, 10F, 3X, 10F, 3X, 10F, 3X, 10F, 3X, 10F",
    # aerodynamics block (12 data lines)
    "CD, 2X, 3X, 10F, 3X, 10F, 3X, 10F, 3X, 10F",
    "CD, 15X, 3X, 10F, 3X, 10F, 3X, 10F",
    "CD, 15X, 3X, 10F, 3X, 10F, 3X, 10F",
    "CD, 15X, 3X, 10F, 3X, 10F, 3X, 10F",
    "CD, 15X, 3X, 10F, 3X, 10F, 3X, 10F",
    "CD, 15X, 3X, 10F, 3X, 10F, 3X, 10F",
    "CD 50X",
    "CD 50X",
    "CD 50X",
    "CD, 31X, 10F",
    "CD 50X",
    "CD 50X",
    # engine thrust block (3 data lines)
    "CD, 2X, 3X, 10F, 3X, 10F, 3X, 10F, 3X, 10F, 3X, 10F",
    "CD, 2X, 3X, 10F, 3X, 10F, 3X, 10F, 3X, 10F, 3X, 10F",
    "CD, 2X, 3X, 10F, 3X, 10F",
    # fuel consumption block (3 data lines)
    "CD, 2X, 3X, 10F, 3X, 10F",
    "CD, 2X, 3X, 10F, 3X, 10F",
    "CD, 5X, 10F",
    # ground movement block (1 data line)
    "CD, 2X, 3X, 10F, 3X, 10F, 3X, 10F, 3X, 10F",
]

APF_FORMAT = [
    "CD, 2X, 3S, 1X, 2S, 4X, 15S",
    "CD, 25X, 3I, 1X, 3I, 1X, 2I, 10X, 3I, 1X, 3I, 1X, 2I, 2X, 2I, 1X, "
    "3I, 1X, 3I",
    "CD, 25X, 3I, 1X, 3I, 1X, 2I, 10X, 3I, 1X, 3I, 1X, 2I, 2X, 2I, 1X, "
    "3I, 1X, 3I",
    "CD, 25X, 3I, 1X, 3I, 1X, 2I, 10X, 3I, 1X, 3I, 1X, 2I, 2X, 2I, 1X, "
    "3I, 1X, 3I",
]

syn_parser = FixedWidthParser(SYN_FORMAT)
opf_parser = FixedWidthParser(OPF_FORMAT)
apf_parser = FixedWidthParser(APF_FORMAT)

# Global model constants (reference ACData class attrs, coeff_bada.py:155-166)
CVMIN = 1.3
CVMIN_TO = 1.2
CRED_TURBOPROP = 0.25
CRED_JET = 0.15
CRED_PISTON = 0.0
GR_ACC = 2.0   # from BADA.gpf


def parse_opf(fname: str) -> dict:
    """One .OPF file -> coefficient dict (cf. ACData.setOPFData,
    coeff_bada.py:167-199)."""
    data = opf_parser.parse(fname)
    d = {}
    d["actype"], d["neng"], d["engtype"], d["weightcat"] = data[0]
    d["actype"] = d["actype"].strip("_")
    (d["m_ref"], d["m_min"], d["m_max"], d["m_paymax"],
     d["mass_grad"]) = data[1]
    d["vmo"], d["mmo"], d["h_mo"], d["h_max"], d["temp_grad"] = data[2]
    d["S"], d["Clbo"], d["k"], d["CM16"] = data[3]
    for i, ph in enumerate(("cr", "ic", "to", "ap", "ld")):
        d[f"vstall_{ph}"], d[f"cd0_{ph}"], d[f"cd2_{ph}"] = data[4 + i]
    d["cd0_gear"] = data[12][0]
    d["ctc"] = data[15]
    (d["ctdes_low"], d["ctdes_high"], d["hp_des"], d["ctdes_app"],
     d["ctdes_land"]) = data[16]
    d["vdes_ref"], d["mdes_ref"] = data[17]
    d["cf1"], d["cf2"] = data[18]
    d["cf3"], d["cf4"] = data[19]
    # guard division by zero in fuel flow (perfbada.py:318-320)
    d["cf2"] = d["cf2"] if abs(d["cf2"]) > 1e-9 else 1.0
    d["cf4"] = d["cf4"] if abs(d["cf4"]) > 1e-9 else 1.0
    d["cf_cruise"] = data[20][0]
    d["tol"], d["ldl"], d["wingspan"], d["length"] = data[21]
    return d


def parse_apf(fname: str) -> dict:
    """One .APF file -> reference-speed profiles (ACData.setAPFData)."""
    data = apf_parser.parse(fname)
    cols = list(zip(*data[1:]))
    keys = ("cascl1", "cascl2", "mcl", "cascr1", "cascr2", "mcr",
            "mdes", "casdes2", "casdes1")
    d = {k: list(v) for k, v in zip(keys, cols)}
    for k in ("mcl", "mcr", "mdes"):
        d[k] = [m / 100.0 for m in d[k]]   # Mach stored *100 in BADA
    return d


def load_bada_dir(path: str) -> Tuple[Dict[str, dict], Dict[str, dict]]:
    """(synonyms, coefficient sets) from a BADA data directory.

    synonyms: {icao_code: {"file": ..., "is_equiv": ..., ...}};
    coeffs: {coeff_file_stem: dict}.  Empty dicts when SYNONYM.NEW is
    absent (the proprietary data is not shipped; coeff_bada.py:107-117).
    """
    synfile = os.path.join(path, "SYNONYM.NEW")
    if not os.path.isfile(synfile):
        return {}, {}
    synonyms = {}
    for row in syn_parser.parse(synfile):
        synonyms[row[1].strip()] = dict(
            is_equiv=(row[0] == "*"), accode=row[1].strip(),
            manufact=row[2].strip(), model=row[3].strip(),
            file=row[4].strip(), icao=(row[5].strip().upper() == "Y"))
    coeffs = {}
    for fname in sorted(glob(os.path.join(path, "*.OPF"))):
        try:
            d = parse_opf(fname)
            apf = fname[:-4] + ".APF"
            if os.path.isfile(apf):
                d.update(parse_apf(apf))
        except (ParseError, IndexError, ValueError):
            continue
        coeffs[d["actype"]] = d
    return synonyms, coeffs


def bada_to_generic(d: dict) -> dict:
    """Map a BADA OPF dict onto the generic PerfArrays column keys.

    Units per the BADA 3.12 manual: masses in tonnes, speeds in kt,
    altitudes in ft, wing area in m2.  Approximations are explicit: the
    engthr column takes the first max-climb thrust coefficient CTC1 (the
    sea-level static value for jets); fuel-flow anchors are evaluated
    from the TSFC law at representative speeds; the full BADA
    thrust/fuel regimes live in ops/perf_bada.py.
    """
    from ..ops import aero
    kts, ft = aero.kts, aero.ft
    jet = d["engtype"].strip().lower().startswith("jet")
    ctc1 = d["ctc"][0]
    engthr = ctc1 if jet else ctc1 / 75.0 * kts  # TP: kt·N at ~150 kt
    # TSFC eta [kg/(min·kN)] -> nominal flows at TO/climb-out/approach/
    # idle representative speeds (perfbada.py:483-520 law)
    def ff_at(tas_kt, thr_frac):
        eta = d["cf1"] * (1.0 + tas_kt / d["cf2"]) / 1000.0
        return eta * engthr * thr_frac / 60.0
    mass_kg = d["m_ref"] * 1000.0
    vminto = CVMIN_TO * d["vstall_to"] * kts
    vminic = CVMIN * d["vstall_ic"] * kts
    vmincr = CVMIN * d["vstall_cr"] * kts
    vminap = CVMIN * d["vstall_ap"] * kts
    vminld = CVMIN * d["vstall_ld"] * kts
    return dict(
        n_engines=int(d["neng"]), wa=d["S"],
        mtow=d["m_max"] * 1000.0, oew=2.0 * mass_kg - d["m_max"] * 1000.0,
        engthr=engthr / max(int(d["neng"]), 1),
        engbpr=6.0 if jet else 0.0,
        ff_to=ff_at(160.0, 1.0), ff_co=ff_at(250.0, 0.85),
        ff_app=ff_at(140.0, 0.3), ff_idl=ff_at(0.0, 0.07),
        cd0_clean=d["cd0_cr"], cd0_gd=d["cd0_cr"] + d["cd0_gear"],
        cd0_to=d["cd0_to"], cd0_ic=d["cd0_ic"],
        cd0_ap=d["cd0_ap"], cd0_ld=d["cd0_ld"] + d["cd0_gear"],
        k=d["cd2_cr"],
        vminto=vminto, vmaxto=vminto * 1.4,
        vminic=vminic, vmaxic=vminic * 1.5,
        vminer=vmincr, vmaxer=d["vmo"] * kts,
        vminap=vminap, vmaxap=vminap * 1.8,
        vminld=vminld, vmaxld=vminld * 1.5,
        vsmin=-3000.0 * aero.fpm, vsmax=2500.0 * aero.fpm,
        hmax=d["h_max"] * ft, axmax=GR_ACC)


def get_coefficients(synonyms, coeffs, actype):
    """Synonym-resolved lookup (coeff_bada.py:72-88); returns dict or
    None."""
    syn = synonyms.get(actype)
    if syn is None:
        return None
    # coefficient files are keyed by the actype stored inside the OPF
    hit = coeffs.get(actype)
    if hit is not None:
        return hit
    stem = re.sub(r"_+$", "", syn["file"])
    return coeffs.get(stem)
