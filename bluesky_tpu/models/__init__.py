"""Aircraft performance coefficient tables and loaders."""
