"""Aircraft performance coefficients: built-in defaults + OpenAP-dir loader.

The reference's OpenAP model loads per-type coefficients from an open data
directory (``data/performance/OpenAP``: aircraft.json, engines.csv,
dragpolar.csv, wrap/*.csv — reference openap/coeff.py:23-160).  This module
provides the same capability two ways:

1. ``load_openap_dir(path)`` parses a directory in the OpenAP layout with
   stdlib csv/json (no pandas) into per-type coefficient dicts.  Point it at
   any OpenAP data checkout via ``settings.perf_path_openap``.
2. ``BUILTIN`` — a compact set of approximate coefficients for common types,
   so the framework runs standalone without any data directory.  Values are
   rounded public airframe/engine figures; they are *defaults*, not a
   substitute for real OpenAP data when fidelity matters.

Host-side creation code calls ``slot_values(actype)`` to get the column
values written into the ``PerfArrays`` slot of a new aircraft.
"""
import csv
import json
import os
from typing import Dict, Optional

import jax.numpy as jnp

# Flight-phase codes (reference openap/phase.py:4-12)
PH_NA, PH_TO, PH_IC, PH_CL, PH_CR, PH_DE, PH_AP, PH_LD, PH_GD = range(9)

KTS = 0.514444
FPM = 0.3048 / 60.0
FT = 0.3048

# Approximate built-in per-type coefficients.  Keys mirror what the OpenAP
# loader produces.  Envelope speeds are CAS [m/s], vs limits [m/s], hmax [m],
# axmax [m/s2]; thr is total static thrust of ONE engine [N]; mass is the
# midpoint of OEW and MTOW like the reference uses (perfoap.py:81).
_A320ISH = dict(
    n_engines=2, wa=122.6, mtow=78000.0, oew=42600.0,
    engthr=120000.0, engbpr=5.7,
    ff_idl=0.10, ff_app=0.32, ff_co=0.95, ff_to=1.17,
    cd0_clean=0.022, cd0_gd=0.055, cd0_to=0.077, cd0_ic=0.042,
    cd0_ap=0.052, cd0_ld=0.120, k=0.037,
    vminto=74.0, vmaxto=96.0, vminic=76.0, vmaxic=90.0,
    vminer=124.0, vmaxer=180.0, vminap=60.0, vmaxap=90.0,
    vminld=55.0, vmaxld=75.0,
    vsmin=-3000.0 * FPM, vsmax=2500.0 * FPM, hmax=12500.0,  # [m] ~FL410
    axmax=1.8,
)

def _variant(base, **kw):
    d = dict(base)
    d.update(kw)
    return d

BUILTIN: Dict[str, dict] = {
    'A320': dict(_A320ISH),
    'A319': _variant(_A320ISH, mtow=70000.0, oew=40800.0, wa=122.6),
    'A321': _variant(_A320ISH, mtow=89000.0, oew=48500.0, wa=122.6,
                     engthr=133000.0),
    'B738': _variant(_A320ISH, mtow=79010.0, oew=41413.0, wa=124.6,
                     engthr=121000.0, engbpr=5.1,
                     cd0_clean=0.020, k=0.040),
    'B744': _variant(_A320ISH, n_engines=4, mtow=396890.0, oew=178756.0,
                     wa=511.0, engthr=276000.0, engbpr=5.0,
                     ff_idl=0.23, ff_app=0.72, ff_co=2.11, ff_to=2.60,
                     cd0_clean=0.021, k=0.043,
                     vminer=140.0, vmaxer=190.0,
                     vsmax=2000.0 * FPM, hmax=13747.0,
                     axmax=1.5),
    'B77W': _variant(_A320ISH, mtow=351533.0, oew=167800.0, wa=436.8,
                     engthr=513000.0, engbpr=8.7,
                     ff_idl=0.30, ff_app=0.95, ff_co=2.85, ff_to=3.50,
                     cd0_clean=0.020, k=0.042, vsmax=2200.0 * FPM,
                     hmax=13140.0, axmax=1.5),
    'E190': _variant(_A320ISH, mtow=51800.0, oew=27720.0, wa=92.5,
                     engthr=82300.0, engbpr=5.0,
                     vminer=115.0, vmaxer=170.0, hmax=12497.0),
}
BUILTIN['NA'] = dict(_A320ISH)  # unknown-type fallback, like reference 'A320'
# fix hmax for the A320-family entries (12.5 km)
for _k in ('A320', 'A319', 'A321', 'B738', 'E190', 'NA'):
    BUILTIN[_k]['hmax'] = min(BUILTIN[_k].get('hmax', 12500.0), 12500.0)


def load_openap_dir(path: str) -> Dict[str, dict]:
    """Parse an OpenAP-layout data directory into per-type coefficient dicts.

    Layout (reference coeff.py:17-21): ``fixwing/aircraft.json``,
    ``fixwing/engines.csv``, ``fixwing/dragpolar.csv``, ``fixwing/wrap/*.csv``.
    Returns {} if the directory is missing; merge the result over BUILTIN.
    """
    fixwing = os.path.join(path, 'fixwing')
    acjson = os.path.join(fixwing, 'aircraft.json')
    if not os.path.exists(acjson):
        return {}

    with open(acjson) as f:
        acs = json.load(f)
    acs.pop('__comment', None)

    engines = {}
    with open(os.path.join(fixwing, 'engines.csv')) as f:
        for row in csv.DictReader(f):
            engines[row['name'].upper()] = row

    dragpolar = {}
    with open(os.path.join(fixwing, 'dragpolar.csv')) as f:
        for row in csv.DictReader(f):
            dragpolar[row['mdl'].upper()] = {
                k: float(v) for k, v in row.items() if k != 'mdl'}

    out = {}
    for mdl, ac in acs.items():
        mdl = mdl.upper()
        # First engine listed that matches the engines table (the reference
        # also uses the first engine, perfoap.py:74-76); all matches are
        # kept for the ENG acid,[engine] change command (perfbase
        # engchange contract).
        eng = None
        avail = {}
        for ename in ac.get('engines', []):
            ename = ename.strip().upper()
            matches = [e for n, e in engines.items() if n.startswith(ename)]
            if matches:
                avail[matches[-1]['name'].upper()] = matches[-1]
                if eng is None:
                    eng = matches[-1]
        if eng is None:
            continue

        d = dict(
            n_engines=int(ac['n_engines']), wa=float(ac['wa']),
            mtow=float(ac['mtow']), oew=float(ac['oew']),
            engthr=float(eng['thr']), engbpr=float(eng['bpr']),
            ff_idl=float(eng['ff_idl']), ff_app=float(eng['ff_app']),
            ff_co=float(eng['ff_co']), ff_to=float(eng['ff_to']),
            engines_avail={n: dict(thr=float(e['thr']),
                                   bpr=float(e['bpr']),
                                   ff_idl=float(e['ff_idl']),
                                   ff_app=float(e['ff_app']),
                                   ff_co=float(e['ff_co']),
                                   ff_to=float(e['ff_to']))
                           for n, e in avail.items()},
        )
        dp = dragpolar.get(mdl) or dragpolar.get('NA')
        if dp is None and dragpolar:
            # mean over all types, like reference coeff.py:37-38
            keys = next(iter(dragpolar.values())).keys()
            dp = {k: sum(v[k] for v in dragpolar.values()) / len(dragpolar)
                  for k in keys}
        if dp:
            d.update({k: dp[k] for k in
                      ('cd0_clean', 'cd0_gd', 'cd0_to', 'cd0_ic',
                       'cd0_ap', 'cd0_ld', 'k')})

        wrapfile = os.path.join(fixwing, 'wrap', mdl.lower() + '.csv')
        if os.path.exists(wrapfile):
            wrap = {}
            with open(wrapfile) as f:
                for row in csv.DictReader(f):
                    wrap[row['param']] = row
            try:
                # Envelope extraction mirrors reference coeff.py:95-140.
                d['vminto'] = float(wrap['to_v_lof']['min'])
                d['vmaxto'] = float(wrap['to_v_lof']['max'])
                d['vminic'] = float(wrap['ic_va_avg']['min'])
                d['vmaxic'] = float(wrap['ic_va_avg']['max'])
                d['vminer'] = min(float(wrap['cl_v_cas_const']['min']),
                                  float(wrap['cr_v_cas_mean']['min']),
                                  float(wrap['de_v_cas_const']['min']))
                # NB: the reference takes the MIN of the phase maxima
                # (coeff.py:91-94) — kept for parity.
                d['vmaxer'] = min(float(wrap['cl_v_cas_const']['max']),
                                  float(wrap['cr_v_cas_mean']['max']),
                                  float(wrap['de_v_cas_const']['max']))
                d['vminap'] = float(wrap['fa_va_avg']['min'])
                d['vmaxap'] = float(wrap['fa_va_avg']['max'])
                d['vminld'] = float(wrap['ld_v_app']['min'])
                d['vmaxld'] = float(wrap['ld_v_app']['max'])
                d['vsmax'] = max(float(wrap['ic_vz_avg']['max']),
                                 float(wrap['cl_vz_avg_pre_cas']['max']),
                                 float(wrap['cl_vz_avg_cas_const']['max']),
                                 float(wrap['cl_vz_avg_mach_const']['max']))
                d['vsmin'] = min(float(wrap['ic_vz_avg']['min']),
                                 float(wrap['de_vz_avg_after_cas']['min']),
                                 float(wrap['de_vz_avg_cas_const']['min']),
                                 float(wrap['de_vz_avg_mach_const']['min']))
                d['hmax'] = float(wrap['cr_h_max']['opt']) * 1000.0
                d['axmax'] = float(wrap['to_acc_tof']['max'])
            except KeyError:
                pass
        # Fill any missing keys from the generic default
        for k, v in _A320ISH.items():
            d.setdefault(k, v)
        d.setdefault('axmax', 1.8)
        out[mdl] = d
    return out


class CoeffDB:
    """Merged coefficient database: BUILTIN overridden by model data.

    ``model`` selects the source (reference traffic.py:39-52 switch):
    'openap' loads the OpenAP directory; 'bs'/'legacy' loads the BS
    conceptual-design XML database mapped onto the generic columns
    (models/coeff_bs.py bs_to_generic); 'bada' loads proprietary BADA
    OPF/APF data when present.  Unknown types fall back to 'NA'
    (the reference's default-B744 behavior, perfbs.py:115-121).
    """

    def __init__(self, openap_path: Optional[str] = None,
                 model: str = "openap", perf_path: Optional[str] = None):
        self.table = dict(BUILTIN)
        self.model = model
        self.bada_synonyms, self.bada_coeffs = {}, {}
        if model in ("bs", "legacy") and perf_path:
            from . import coeff_bs
            bsdir = os.path.join(perf_path, "BS")
            self.table.update({t: coeff_bs.bs_to_generic(d)
                               for t, d in
                               coeff_bs.load_bs_dir(bsdir).items()})
        elif model == "bada" and perf_path:
            from . import coeff_bada
            syn, coeffs = coeff_bada.load_bada_dir(
                os.path.join(perf_path, "BADA"))
            self.bada_synonyms, self.bada_coeffs = syn, coeffs
            for code in syn:
                d = coeff_bada.get_coefficients(syn, coeffs, code)
                if d is not None:
                    self.table[code.upper()] = coeff_bada.bada_to_generic(d)
        elif openap_path:
            loaded = load_openap_dir(openap_path)
            if not loaded:
                # an explicitly-given path with no data is caller error
                # territory; the default-path fallback notice lives at
                # the resolution point (core/traffic.py)
                print(f"perf: no coefficient data at {openap_path} — "
                      "using the BUILTIN approximate set "
                      f"({len(BUILTIN)} types; unknown types map to 'NA')")
            self.table.update(loaded)

    def get(self, actype: str) -> dict:
        return self.table.get(actype.upper(), self.table['NA'])


def slot_values(coeffs: dict) -> dict:
    """PerfArrays column values for one aircraft from a coefficient dict."""
    from .. import models  # noqa: F401  (package anchor)
    from ..ops import aero
    ffa, ffb, ffc = _ff_quadratic(coeffs['ff_idl'], coeffs['ff_app'],
                                  coeffs['ff_co'], coeffs['ff_to'])
    return dict(
        mass=0.5 * (coeffs['oew'] + coeffs['mtow']),
        sref=coeffs['wa'],
        engthrust=coeffs['engthr'],
        engbpr=coeffs['engbpr'],
        engnum=float(coeffs['n_engines']),
        ff_a=ffa, ff_b=ffb, ff_c=ffc,
        cd0_clean=coeffs['cd0_clean'], cd0_gd=coeffs['cd0_gd'],
        cd0_to=coeffs['cd0_to'], cd0_ic=coeffs['cd0_ic'],
        cd0_ap=coeffs['cd0_ap'], cd0_ld=coeffs['cd0_ld'], k=coeffs['k'],
        vminto=coeffs['vminto'], vminic=coeffs['vminic'],
        vminer=coeffs['vminer'], vminap=coeffs['vminap'],
        vminld=coeffs['vminld'],
        vmaxto=coeffs['vmaxto'], vmaxic=coeffs['vmaxic'],
        vmaxer=coeffs['vmaxer'], vmaxap=coeffs['vmaxap'],
        vmaxld=coeffs['vmaxld'],
        vsmin=coeffs['vsmin'], vsmax=coeffs['vsmax'],
        hmax=coeffs['hmax'], axmax=coeffs['axmax'],
        islifttype_rotor=False,
    )


def _ff_quadratic(ffidl, ffapp, ffco, ffto):
    """Quadratic fuel-flow fit through the 4 ICAO points.

    The reference fits ff = a*tr^2 + b*tr + c through thrust-ratio points
    (0.07, 0.3, 0.85, 1.0) (openap/thrust.py compute_eng_ff_coeff).  A plain
    least-squares fit through the same points, computed host-side once per
    engine type.
    """
    import numpy as np
    x = np.array([0.07, 0.3, 0.85, 1.0])
    y = np.array([ffidl, ffapp, ffco, ffto])
    a, b, c = np.polyfit(x, y, 2)
    return float(a), float(b), float(c)


def empty_perf_arrays(nmax: int, dtype):
    """Allocate PerfArrays filled with the generic default coefficients."""
    from ..core.state import PerfArrays
    vals = slot_values(BUILTIN['NA'])

    def full(v):
        return jnp.full((nmax,), float(v), dtype)

    return PerfArrays(
        mass=full(vals['mass']), sref=full(vals['sref']),
        engthrust=full(vals['engthrust']), engbpr=full(vals['engbpr']),
        ff_a=full(vals['ff_a']), ff_b=full(vals['ff_b']),
        ff_c=full(vals['ff_c']), engnum=full(vals['engnum']),
        cd0_clean=full(vals['cd0_clean']), cd0_gd=full(vals['cd0_gd']),
        cd0_to=full(vals['cd0_to']), cd0_ic=full(vals['cd0_ic']),
        cd0_ap=full(vals['cd0_ap']), cd0_ld=full(vals['cd0_ld']),
        k=full(vals['k']),
        vminto=full(vals['vminto']), vminic=full(vals['vminic']),
        vminer=full(vals['vminer']), vminap=full(vals['vminap']),
        vminld=full(vals['vminld']),
        vmaxto=full(vals['vmaxto']), vmaxic=full(vals['vmaxic']),
        vmaxer=full(vals['vmaxer']), vmaxap=full(vals['vmaxap']),
        vmaxld=full(vals['vmaxld']),
        vsmin=full(vals['vsmin']), vsmax=full(vals['vsmax']),
        hmax=full(vals['hmax']), axmax=full(vals['axmax']),
        islifttype_rotor=jnp.zeros((nmax,), dtype=bool),
        phase=jnp.zeros((nmax,), jnp.int32),
        vmin=full(0.0), vmax=full(vals['vmaxer']),
        thrust=full(0.0), drag=full(0.0), fuelflow=full(0.0),
    )
