"""Fixed-width column text parser (BADA OPF/APF file format).

Same spec grammar as the reference ``tools/fwparser.py`` (taken from the
BADA manual's fortran-like format lines): each spec line starts with a
line discriminator (e.g. ``CD``) followed by comma-separated fields —
``3X`` skips 3 columns, ``10F`` reads a 10-char float, ``5I`` an int,
``6S`` a string.

Implementation divergence from the reference: the spec is compiled to
explicit (start, end, type) slices instead of a regex assembled from
substitution passes — same accepted inputs, clearer failure modes, and a
``ParseError`` carrying file/line context.
"""
import re
from typing import List

_FIELD = re.compile(r"\s*(\d+)\s*([XFIS])\s*$", re.IGNORECASE)

_TYPES = {"f": float, "i": int, "s": str}


class ParseError(Exception):
    def __init__(self, fname, lineno):
        super().__init__(f"parse error in {fname}:{lineno}")
        self.fname = fname
        self.lineno = lineno


class FixedWidthParser:
    def __init__(self, specformat: List[str]):
        # Single-line specs repeat for every matching line (fwparser.py:47)
        self.repeat = len(specformat) == 1
        self.lines = []
        for spec in specformat:
            parts = [p.strip() for p in spec.split(",")]
            head = parts[0].split()
            discriminator = head[0]
            rest = head[1:] + parts[1:]
            pos = len(discriminator)
            fields = []   # (start, end, converter)
            for tok in rest:
                if not tok:
                    continue
                m = _FIELD.match(tok)
                if not m:
                    raise ValueError(f"bad field spec {tok!r} in {spec!r}")
                width = int(m.group(1))
                kind = m.group(2).lower()
                if kind != "x":
                    fields.append((pos, pos + width, _TYPES[kind]))
                pos += width
            self.lines.append((discriminator, fields))

    def parse(self, fname: str):
        """Returns a list of per-matched-line value lists."""
        disc, fields = self.lines[0]
        data = []
        with open(fname) as f:
            for lineno, line in enumerate(f):
                if not line.startswith(disc):
                    continue
                try:
                    row = [conv(line[a:b].strip())
                           for a, b, conv in fields]
                except ValueError:
                    raise ParseError(fname, lineno + 1)
                data.append(row)
                if not self.repeat:
                    if len(data) == len(self.lines):
                        break
                    disc, fields = self.lines[len(data)]
        return data
