"""The simulation state: a padded struct-of-arrays JAX pytree.

This replaces the reference's ``TrafficArrays`` registry
(``bluesky/tools/trafficarrays.py:19-138``), which grows NumPy arrays with
``np.append`` on every aircraft creation.  Dynamic shapes are poison for XLA
— every growth would recompile — so the single most consequential design
divergence from the reference is here:

* Every per-aircraft array has fixed shape ``[N_max]`` (pair matrices
  ``[N_max, N_max]``, waypoint tables ``[N_max, W_max]``).
* A boolean ``active`` mask marks live slots; create/delete are mask flips +
  slot writes (functional ``.at[].set``), never reshapes.
* Callsign/type strings and other host-only bookkeeping live OUTSIDE the
  pytree in the host-side ``Traffic`` facade (core/traffic.py), so the device
  never sees a Python object.

All sub-structures are `flax.struct` dataclasses => they are pytrees: they
jit, vmap, shard and donate cleanly.  Field groups mirror the reference's
state registration (traffic.py:91-164, activewpdata.py:12-20, autopilot
state autopilot.py:24-43, pilot.py:12-17, asas state) so every reference
variable has a home; dtype is configurable (float32 for TPU throughput,
float64 on CPU for golden tests).
"""
from typing import Optional

import jax
import jax.numpy as jnp
from flax import struct

from ..ops import aero


#: worst-case extra padded slots of the sparse backend's stripe-sorted
#: layout: 32 pad blocks of <= 256 slots plus block rounding
#: (ops/cd_sched.stripe_sort_dest with block <= 256, extra_blocks = 32).
SORT_PAD = 33 * 256


@struct.dataclass
class AircraftArrays:
    """Kinematic + autopilot-selection state, one row per aircraft slot.

    Mirrors reference traffic.py:91-164.
    """
    active: jnp.ndarray   # bool — live slot mask (replaces dynamic ntraf)
    # Position
    lat: jnp.ndarray      # [deg]
    lon: jnp.ndarray      # [deg]
    alt: jnp.ndarray      # [m]
    hdg: jnp.ndarray      # [deg] heading
    trk: jnp.ndarray      # [deg] ground track
    # Velocity
    tas: jnp.ndarray      # [m/s] true airspeed
    gs: jnp.ndarray       # [m/s] ground speed
    gsnorth: jnp.ndarray  # [m/s]
    gseast: jnp.ndarray   # [m/s]
    cas: jnp.ndarray      # [m/s] calibrated airspeed
    mach: jnp.ndarray     # [-]
    vs: jnp.ndarray       # [m/s] vertical speed
    # Atmosphere at current altitude
    p: jnp.ndarray        # [Pa]
    rho: jnp.ndarray      # [kg/m3]
    temp: jnp.ndarray     # [K]
    # Autopilot selections (the MCP panel)
    selspd: jnp.ndarray   # selected CAS [m/s] or Mach [-]
    selalt: jnp.ndarray   # [m]
    selvs: jnp.ndarray    # [m/s]
    # LNAV/VNAV mode switches
    swlnav: jnp.ndarray   # bool
    swvnav: jnp.ndarray   # bool
    # Performance-ish per-aircraft settings (traffic.py:140-149)
    apvsdef: jnp.ndarray  # [m/s] default AP vertical speed
    aphi: jnp.ndarray     # [rad] AP bank-angle setting
    ax: jnp.ndarray       # [m/s2] longitudinal acceleration (abs)
    bank: jnp.ndarray     # [rad] nominal bank angle
    swhdgsel: jnp.ndarray  # bool — currently turning
    swaltsel: jnp.ndarray  # bool — currently climbing/descending
    # Crossover altitude flags
    abco: jnp.ndarray     # bool — above crossover
    belco: jnp.ndarray    # bool — below crossover
    # Misc
    coslat: jnp.ndarray   # cos(lat) cache for flat-earth math


@struct.dataclass
class ActWpArrays:
    """Active-leg guidance state (reference activewpdata.py:12-20)."""
    lat: jnp.ndarray        # [deg] active waypoint latitude
    lon: jnp.ndarray        # [deg]
    nextaltco: jnp.ndarray  # [m] next altitude constraint
    xtoalt: jnp.ndarray     # [m] distance from next wp to that constraint
    spd: jnp.ndarray        # CAS [m/s] / Mach — active wp speed (-999 = none)
    vs: jnp.ndarray         # [m/s] VNAV vertical speed to use
    turndist: jnp.ndarray   # [m] turn-anticipation distance
    flyby: jnp.ndarray      # 1.0 fly-by / 0.0 fly-over
    next_qdr: jnp.ndarray   # [deg] track of next leg (-999 = unknown)


@struct.dataclass
class AutopilotArrays:
    """FMS guidance output state (reference autopilot.py:24-43)."""
    trk: jnp.ndarray       # [deg] commanded track
    tas: jnp.ndarray       # [m/s] commanded TAS
    alt: jnp.ndarray       # [m] commanded altitude
    vs: jnp.ndarray        # [m/s] commanded vertical speed
    dist2vs: jnp.ndarray   # [m] distance-to-waypoint where descent starts
    swvnavvs: jnp.ndarray  # bool — VNAV vertical guidance engaged
    vnavvs: jnp.ndarray    # [m/s] VNAV vertical speed


@struct.dataclass
class PilotArrays:
    """AP-vs-ASAS arbitrated targets (reference pilot.py:12-17)."""
    alt: jnp.ndarray
    hdg: jnp.ndarray
    trk: jnp.ndarray
    vs: jnp.ndarray
    tas: jnp.ndarray


@struct.dataclass
class AsasArrays:
    """Conflict detection & resolution state (reference asas.py + MVP).

    ``resopairs`` is the [N,N] pair matrix replacing the reference's Python
    set of callsign tuples (asas.py:417); ``active`` is the per-aircraft
    "follow ASAS, not AP" flag consumed by the pilot arbitration.

    For the tiled large-N backend (ops/cd_tiled.py) ``resopairs`` is
    allocated [0,0] (an [N,N] bool is 10 GB at N=100k) and the resume-nav
    pair memory lives in ``partners``: [N,K] intruder indices, -1 = empty.
    """
    trk: jnp.ndarray        # [deg] resolution track command
    tas: jnp.ndarray        # [m/s] resolution speed command
    vs: jnp.ndarray         # [m/s] resolution vertical-speed command
    alt: jnp.ndarray        # [m] resolution altitude command
    active: jnp.ndarray     # [N] bool
    inconf: jnp.ndarray     # [N] bool — in conflict right now
    tcpamax: jnp.ndarray    # [N] max tcpa over own conflicts
    resopairs: jnp.ndarray  # [N,N] bool — pairs still being resolved
    partners: jnp.ndarray   # [N,K] int32 — tiled-backend partner table
    asasn: jnp.ndarray      # [N] resolution-vector north (display/logs)
    asase: jnp.ndarray      # [N] resolution-vector east
    noreso: jnp.ndarray     # [N] bool — nobody avoids these aircraft
    resooff: jnp.ndarray    # [N] bool — these aircraft don't resolve
    # Cumulative counts (device-side; unique-pair sets stay host-side)
    nconf_cur: jnp.ndarray  # scalar int — current directional conflict pairs
    nlos_cur: jnp.ndarray   # scalar int — current LoS pairs
    # Cached spatial sort for the tiled/pallas/sparse backends (Morton
    # permutation, or padded stripe destinations for 'sparse').  Sorting
    # 100k keys on TPU costs more than the CD kernel itself, and ANY
    # layout is exact (results are mapped back; tile reachability is
    # recomputed from true positions every interval) — so the sort is
    # refreshed by the HOST at chunk boundaries
    # (core/asas.refresh_spatial_sort) and carried here.
    sort_perm: jnp.ndarray  # [N] int32 — slot permutation / stripe dest
    # Sorted-space partner table for the 'sparse' backend: rows are
    # PADDED-SORTED slots (layout of ops/cd_sched.stripe_sort_dest,
    # bounded by SORT_PAD extra slots), values are sorted-slot ids, -1
    # empty.  Lives in sorted space so the in-kernel resume-nav needs no
    # [N,K] gathers; remapped on host sort refreshes.  The other
    # backends keep using ``partners`` (caller-slot semantics).
    partners_s: jnp.ndarray  # [N + SORT_PAD, K] int32


@struct.dataclass
class RouteArrays:
    """Dense per-aircraft flight plans: [N_max, W_max] waypoint tables.

    Replaces the reference's per-aircraft Python ``Route`` objects
    (route.py:15-1109).  Route *editing* (stack commands) happens host-side
    in core/route.py, which writes these tables; the device only reads them.
    ``wptoalt``/``wpxtoalt`` carry the propagated altitude-constraint
    lookahead that the reference computes in ``Route.calcfp``
    (route.py:983-1041), so the jitted FMS never scans the route.
    """
    wplat: jnp.ndarray    # [N,W] deg
    wplon: jnp.ndarray    # [N,W] deg
    wpalt: jnp.ndarray    # [N,W] m      (-999 = no constraint)
    wpspd: jnp.ndarray    # [N,W] CAS/Mach (-999 = no constraint)
    wpflyby: jnp.ndarray  # [N,W] 1.0 fly-by / 0.0 fly-over
    wptoalt: jnp.ndarray  # [N,W] m   next alt constraint at/after this wp
    wpxtoalt: jnp.ndarray  # [N,W] m  distance from this wp to that constraint
    nwp: jnp.ndarray      # [N] int32 — number of valid waypoints
    iactwp: jnp.ndarray   # [N] int32 — index of active waypoint (-1 = none)


@struct.dataclass
class PerfArrays:
    """Vectorized OpenAP-style performance model state (core/perf.py).

    Per-aircraft coefficient columns are filled host-side at creation from
    the type tables (models/perf_coeffs.py); phase-dependent selection
    happens in the jitted update.  Mirrors reference perfoap.py:28-47.
    """
    mass: jnp.ndarray       # [kg]
    sref: jnp.ndarray       # [m2] wing area
    engthrust: jnp.ndarray  # [N] total static thrust (n_eng * per-engine)
    engbpr: jnp.ndarray     # engine bypass ratio
    ff_a: jnp.ndarray       # fuel-flow quadratic coefficients
    ff_b: jnp.ndarray
    ff_c: jnp.ndarray
    engnum: jnp.ndarray     # number of engines
    cd0_clean: jnp.ndarray
    cd0_gd: jnp.ndarray
    cd0_to: jnp.ndarray
    cd0_ic: jnp.ndarray
    cd0_ap: jnp.ndarray
    cd0_ld: jnp.ndarray
    k: jnp.ndarray          # induced-drag factor
    # Phase-dependent envelope columns [N] (vmin/vmax per phase group)
    vminto: jnp.ndarray     # CAS m/s
    vminic: jnp.ndarray
    vminer: jnp.ndarray
    vminap: jnp.ndarray
    vminld: jnp.ndarray
    vmaxto: jnp.ndarray
    vmaxic: jnp.ndarray
    vmaxer: jnp.ndarray
    vmaxap: jnp.ndarray
    vmaxld: jnp.ndarray
    vsmin: jnp.ndarray      # m/s
    vsmax: jnp.ndarray      # m/s
    hmax: jnp.ndarray       # m
    axmax: jnp.ndarray      # m/s2
    islifttype_rotor: jnp.ndarray  # bool
    # Outputs of the jitted perf update
    phase: jnp.ndarray      # int32 flight phase
    vmin: jnp.ndarray       # current phase envelope
    vmax: jnp.ndarray
    thrust: jnp.ndarray     # [N]
    drag: jnp.ndarray       # [N]
    fuelflow: jnp.ndarray   # [kg/s]


@struct.dataclass
class SimState:
    """Top-level simulation state — one pytree, jitted/donated whole."""
    ac: AircraftArrays
    actwp: ActWpArrays
    ap: AutopilotArrays
    pilot: PilotArrays
    asas: AsasArrays
    route: RouteArrays
    perf: PerfArrays
    adsb: "AdsbArrays"      # noise.AdsbArrays — surveillance broadcast state
    wind: "WindState"       # wind.WindState — point-defined wind field
    rng: jnp.ndarray        # PRNG key for turbulence/ADS-B noise
    simt: jnp.ndarray       # [s] simulation time (scalar)
    fms_t0: jnp.ndarray     # [s] last FMS update time (autopilot.py:17)
    asas_tnext: jnp.ndarray  # [s] next ASAS trigger time (asas.py:474-478)

    @property
    def nmax(self) -> int:
        return self.ac.lat.shape[0]


def _zeros(nmax, dtype):
    return jnp.zeros((nmax,), dtype=dtype)


def make_state(nmax: int = 64, wmax: int = 32,
               dtype=jnp.float32, rng_seed: int = 0,
               pair_matrix: bool = True, k_partners: int = 8) -> SimState:
    """Allocate an empty padded simulation state.

    Defaults mirror the reference's creation defaults where a slot is
    activated (traffic.py:287-308, activewpdata.py:22-29); padding slots hold
    benign values (eps speeds, lat 89.99 for waypoints) so jitted math stays
    NaN-free without branching.
    """
    f = lambda: _zeros(nmax, dtype)
    b = lambda: jnp.zeros((nmax,), dtype=bool)
    i = lambda: jnp.zeros((nmax,), dtype=jnp.int32)

    ac = AircraftArrays(
        active=b(), lat=f(), lon=f(), alt=f(), hdg=f(), trk=f(),
        tas=f(), gs=f(), gsnorth=f(), gseast=f(), cas=f(), mach=f(), vs=f(),
        p=f(), rho=f(), temp=f(),
        selspd=f(), selalt=f(), selvs=f(),
        swlnav=b(), swvnav=b(),
        apvsdef=jnp.full((nmax,), 1500.0 * aero.fpm, dtype),
        aphi=jnp.full((nmax,), jnp.radians(25.0), dtype),
        ax=jnp.full((nmax,), aero.kts, dtype),
        bank=jnp.full((nmax,), jnp.radians(25.0), dtype),
        swhdgsel=b(), swaltsel=b(),
        abco=b(), belco=jnp.ones((nmax,), dtype=bool),
        coslat=jnp.ones((nmax,), dtype),
    )
    actwp = ActWpArrays(
        lat=jnp.full((nmax,), 89.99, dtype), lon=f(),
        nextaltco=f(), xtoalt=f(),
        spd=jnp.full((nmax,), -999.0, dtype), vs=f(),
        turndist=jnp.ones((nmax,), dtype),
        flyby=jnp.ones((nmax,), dtype),
        next_qdr=jnp.full((nmax,), -999.0, dtype),
    )
    ap = AutopilotArrays(
        trk=f(), tas=f(), alt=f(), vs=f(),
        dist2vs=jnp.full((nmax,), -999.0, dtype),
        swvnavvs=b(), vnavvs=f(),
    )
    pilot = PilotArrays(alt=f(), hdg=f(), trk=f(), vs=f(), tas=f())
    asas = AsasArrays(
        trk=f(), tas=f(), vs=f(), alt=f(),
        active=b(), inconf=b(), tcpamax=f(),
        resopairs=jnp.zeros((nmax, nmax) if pair_matrix else (0, 0),
                            dtype=bool),
        partners=jnp.full((nmax, k_partners), -1, jnp.int32),
        asasn=f(), asase=f(), noreso=b(), resooff=b(),
        nconf_cur=jnp.zeros((), jnp.int32), nlos_cur=jnp.zeros((), jnp.int32),
        sort_perm=jnp.arange(nmax, dtype=jnp.int32),
        partners_s=jnp.full((nmax + SORT_PAD, k_partners), -1, jnp.int32),
    )
    route = RouteArrays(
        wplat=jnp.full((nmax, wmax), 89.99, dtype),
        wplon=jnp.zeros((nmax, wmax), dtype),
        wpalt=jnp.full((nmax, wmax), -999.0, dtype),
        wpspd=jnp.full((nmax, wmax), -999.0, dtype),
        wpflyby=jnp.ones((nmax, wmax), dtype),
        wptoalt=jnp.full((nmax, wmax), -999.0, dtype),
        wpxtoalt=jnp.zeros((nmax, wmax), dtype),
        nwp=i(), iactwp=jnp.full((nmax,), -1, jnp.int32),
    )
    from ..models import perf_coeffs
    from . import noise, wind as windmod
    perf = perf_coeffs.empty_perf_arrays(nmax, dtype)
    return SimState(
        ac=ac, actwp=actwp, ap=ap, pilot=pilot, asas=asas, route=route,
        perf=perf,
        adsb=noise.make_adsb(nmax, dtype),
        wind=windmod.make_windstate(dtype=dtype),
        rng=jax.random.PRNGKey(rng_seed),
        simt=jnp.zeros((), dtype),
        fms_t0=jnp.full((), -999.0, dtype),
        asas_tnext=jnp.zeros((), dtype),
    )
