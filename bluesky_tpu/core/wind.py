"""Wind field: fixed-capacity point-defined field with altitude profiles.

Parity with reference ``bluesky/traffic/windfield.py`` (+ the ``WindSim``
stack adapter in ``windsim.py``): wind vectors are defined at lat/lon points,
optionally with altitude profiles resampled onto a fixed altitude axis;
queries interpolate inverse-distance-squared horizontally and linearly in
altitude (windfield.py:123-213).

TPU-first: the reference appends columns to a growing (nalt, nvec) matrix.
Here the field is a fixed-capacity ``[PMAX, KALT]`` pytree with an active
mask — adding/removing points is a host-side slot write, queries are one
fused gather+reduction that vmaps over aircraft.  The 0/1/2/3-D dimension
dance of the reference collapses: inactive points get zero weight, a single
point degenerates to constant wind, and constant-profile points just hold a
constant row — no branching.
"""
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..ops import aero

ALTMAX = 45000.0 * aero.ft
ALTSTEP = 100.0 * aero.ft   # reference windfield.py:43-44
KALT = int(ALTMAX / ALTSTEP) + 1


@struct.dataclass
class WindState:
    """Fixed-capacity wind field (device side)."""
    lat: jnp.ndarray      # [P] deg
    lon: jnp.ndarray      # [P] deg
    vnorth: jnp.ndarray   # [P,K] m/s on the fixed altitude axis
    veast: jnp.ndarray    # [P,K] m/s
    active: jnp.ndarray   # [P] bool
    winddim: jnp.ndarray  # scalar int: 0 none, 1 const, 2 planar, 3 profiles


def make_windstate(pmax: int = 16, dtype=jnp.float32) -> WindState:
    return WindState(
        lat=jnp.zeros((pmax,), dtype), lon=jnp.zeros((pmax,), dtype),
        vnorth=jnp.zeros((pmax, KALT), dtype),
        veast=jnp.zeros((pmax, KALT), dtype),
        active=jnp.zeros((pmax,), dtype=bool),
        winddim=jnp.zeros((), jnp.int32))


def add_point(wind: WindState, lat, lon, winddir, windspd,
              windalt=None) -> WindState:
    """Host-side: write a wind point into the first free slot.

    winddir [deg] is the direction the wind comes FROM (the +pi in reference
    windfield.py:84-92 converts to the blow-to vector).  windspd [m/s].
    With ``windalt`` (list), dir/spd are arrays per altitude, linearly
    resampled onto the fixed axis.
    """
    altaxis = np.arange(0.0, KALT) * ALTSTEP
    if windalt is None:
        wdir = np.full(KALT, float(np.atleast_1d(winddir)[0]))
        wspd = np.full(KALT, float(np.atleast_1d(windspd)[0]))
        vn = wspd * np.cos(np.radians(wdir) + np.pi)
        ve = wspd * np.sin(np.radians(wdir) + np.pi)
        prof3d = False
    else:
        wdir = np.asarray(winddir, dtype=float)
        wspd = np.asarray(windspd, dtype=float)
        altvn = wspd * np.cos(np.radians(wdir) + np.pi)
        altve = wspd * np.sin(np.radians(wdir) + np.pi)
        vn = np.interp(altaxis, np.asarray(windalt, dtype=float), altvn)
        ve = np.interp(altaxis, np.asarray(windalt, dtype=float), altve)
        prof3d = True

    free = np.where(~np.asarray(wind.active))[0]
    if len(free) == 0:
        raise ValueError("wind field full; increase pmax")
    i = int(free[0])
    nactive = int(np.sum(np.asarray(wind.active))) + 1
    winddim = int(wind.winddim)
    if winddim < 3:
        winddim = min(2, nactive)
    if prof3d:
        winddim = 3
    return wind.replace(
        lat=wind.lat.at[i].set(float(lat)),
        lon=wind.lon.at[i].set(float(lon)),
        vnorth=wind.vnorth.at[i].set(jnp.asarray(vn, wind.vnorth.dtype)),
        veast=wind.veast.at[i].set(jnp.asarray(ve, wind.veast.dtype)),
        active=wind.active.at[i].set(True),
        winddim=jnp.asarray(winddim, jnp.int32))


def getdata(wind: WindState, lat, lon, alt):
    """Wind (vnorth, veast) [m/s] at positions — jit-safe.

    Inverse-distance-squared horizontal weights over active points, linear
    interpolation on the altitude axis (reference windfield.py:155-205).
    Returns zeros when no points are defined.
    """
    eps = 1e-20
    cavelat = jnp.cos(jnp.radians(0.5 * (lat[None, :] + wind.lat[:, None])))
    dy = lat[None, :] - wind.lat[:, None]
    dx = cavelat * (lon[None, :] - wind.lon[:, None])
    invd2 = wind.active[:, None] / (eps + dx * dx + dy * dy)   # [P, N]
    total = jnp.maximum(jnp.sum(invd2, axis=0, keepdims=True), 1e-30)
    horfact = invd2 / total                                    # [P, N]

    idxalt = jnp.maximum(0.0, jnp.minimum(ALTMAX - 1e-6, alt)) / ALTSTEP
    ialt = jnp.floor(idxalt).astype(jnp.int32)
    falt = idxalt - ialt

    vn_lo = wind.vnorth[:, :].T[ialt, :]       # [N, P] rows at lower level
    vn_hi = wind.vnorth[:, :].T[jnp.minimum(ialt + 1, KALT - 1), :]
    ve_lo = wind.veast[:, :].T[ialt, :]
    ve_hi = wind.veast[:, :].T[jnp.minimum(ialt + 1, KALT - 1), :]

    w = horfact.T                               # [N, P]
    vnorth = (1.0 - falt) * jnp.sum(vn_lo * w, axis=1) \
        + falt * jnp.sum(vn_hi * w, axis=1)
    veast = (1.0 - falt) * jnp.sum(ve_lo * w, axis=1) \
        + falt * jnp.sum(ve_hi * w, axis=1)

    haswind = wind.winddim > 0
    return jnp.where(haswind, vnorth, 0.0), jnp.where(haswind, veast, 0.0)
