"""Aircraft kinematics: airspeed/heading/VS dynamics + WGS-84 integration.

Pure-function parity with the reference's ``Traffic.UpdateAirSpeed /
UpdateGroundSpeed / UpdatePosition`` (traffic.py:425-483): first-order
acceleration toward the pilot-commanded TAS, bank-limited turn toward the
commanded heading, fixed-acceleration vertical-speed capture, wind-vector
addition, and explicit-Euler integration of lat/lon on the mean-radius
sphere.  All elementwise over the padded aircraft axis — XLA fuses the whole
thing into a couple of kernels.
"""
import jax.numpy as jnp

from ..ops import aero


def update_airspeed(ac, pilot, accel, simdt, eps=0.01, smooth=None):
    """TAS/heading/VS dynamics toward pilot targets (traffic.py:425-454).

    Args:
      ac:     AircraftArrays
      pilot:  PilotArrays (arbitrated targets)
      accel:  [N] per-aircraft acceleration magnitude [m/s2] (perf model)
      smooth: diff.smooth.SmoothConfig or None.  The hard dynamics are
              bang-bang (``sign(error) * rate`` under a dead-band) —
              zero gradient in the targets everywhere.  Smooth mode
              advances by a straight-through-clipped proportional step
              (diff/smooth.capture_step): identical full-rate steps
              outside the dead-band, exact capture inside it, and a
              backward pass that carries d(state)/d(target) through
              the saturation (docs/PERF_ANALYSIS.md §differentiable).
    Returns updated AircraftArrays (tas/cas/mach, hdg, vs, ax, swhdgsel,
    swaltsel updated).
    """
    if smooth is not None:
        return _update_airspeed_smooth(ac, pilot, accel, simdt, eps, smooth)
    # Horizontal acceleration toward commanded TAS, dead-banded at 1 kt
    delta_spd = pilot.tas - ac.tas
    need_ax = jnp.abs(delta_spd) > aero.kts
    ax = need_ax * jnp.sign(delta_spd) * accel
    tas = ac.tas + ax * simdt
    cas = aero.vtas2cas(tas, ac.alt)
    mach = aero.vtas2mach(tas, ac.alt)

    # Bank-limited turn toward commanded heading
    turnrate = jnp.degrees(aero.g0 * jnp.tan(ac.bank)
                           / jnp.maximum(tas, eps))
    delhdg = (pilot.hdg - ac.hdg + 180.0) % 360.0 - 180.0
    swhdgsel = jnp.abs(delhdg) > jnp.abs(2.0 * simdt * turnrate)
    hdg = (ac.hdg + simdt * turnrate * swhdgsel * jnp.sign(delhdg)) % 360.0

    # Vertical-speed capture toward commanded altitude: the target VS keeps
    # the commanded magnitude |pilot.vs| signed toward the altitude error;
    # VS itself changes at a fixed 300 fpm/s (~1.6 m/s2) acceleration.
    delta_alt = pilot.alt - ac.alt
    swaltsel = jnp.abs(delta_alt) > jnp.maximum(
        10.0 * aero.ft, jnp.abs(2.0 * simdt * jnp.abs(ac.vs)))
    target_vs = swaltsel * jnp.sign(delta_alt) * jnp.abs(pilot.vs)
    delta_vs = target_vs - ac.vs
    need_az = jnp.abs(delta_vs) > 300.0 * aero.fpm
    az = need_az * jnp.sign(delta_vs) * (300.0 * aero.fpm)
    vs = jnp.where(need_az, ac.vs + az * simdt, target_vs)
    vs = jnp.where(jnp.isfinite(vs), vs, 0.0)

    return ac.replace(tas=tas, cas=cas, mach=mach, hdg=hdg, vs=vs, ax=ax,
                      swhdgsel=swhdgsel, swaltsel=swaltsel)


def _update_airspeed_smooth(ac, pilot, accel, simdt, eps, smooth):
    """The differentiable relaxation of ``update_airspeed`` (called only
    with ``SimConfig.smooth`` set — never on the serving path).  Each
    bang-bang capture becomes ``capture_step``: same saturated rate
    toward the target, exact capture instead of dead-band chatter,
    straight-through backward."""
    from ..diff.smooth import capture_step

    delta_spd = pilot.tas - ac.tas
    dtas = capture_step(delta_spd, accel * simdt)
    tas = ac.tas + dtas
    ax = dtas / simdt
    cas = aero.vtas2cas(tas, ac.alt)
    mach = aero.vtas2mach(tas, ac.alt)

    turnrate = jnp.degrees(aero.g0 * jnp.tan(ac.bank)
                           / jnp.maximum(tas, eps))
    delhdg = (pilot.hdg - ac.hdg + 180.0) % 360.0 - 180.0
    swhdgsel = jnp.abs(delhdg) > jnp.abs(2.0 * simdt * turnrate)
    hdg = (ac.hdg + capture_step(delhdg, simdt * turnrate)) % 360.0

    # VS toward the rate that would close the altitude error in one
    # step, capped at the commanded |pilot.vs| (sign falls out of the
    # error); VS itself still slews at the fixed 300 fpm/s.
    delta_alt = pilot.alt - ac.alt
    swaltsel = jnp.abs(delta_alt) > jnp.maximum(
        10.0 * aero.ft, jnp.abs(2.0 * simdt * jnp.abs(ac.vs)))
    target_vs = capture_step(delta_alt / simdt, jnp.abs(pilot.vs))
    vs = ac.vs + capture_step(target_vs - ac.vs,
                              300.0 * aero.fpm * simdt)
    vs = jnp.where(jnp.isfinite(vs), vs, 0.0)

    return ac.replace(tas=tas, cas=cas, mach=mach, hdg=hdg, vs=vs, ax=ax,
                      swhdgsel=swhdgsel, swaltsel=swaltsel)


def update_groundspeed(ac, windn=None, winde=None):
    """Ground-speed/track from heading, TAS and wind (traffic.py:456-476).

    windn/winde: [N] wind components at aircraft positions, or None for calm.
    """
    hdgrad = jnp.radians(ac.hdg)
    tasnorth = ac.tas * jnp.cos(hdgrad)
    taseast = ac.tas * jnp.sin(hdgrad)
    if windn is None:
        return ac.replace(gsnorth=tasnorth, gseast=taseast,
                          gs=ac.tas, trk=ac.hdg)
    # Wind applies only when airborne (alt > 50 ft)
    airborne = ac.alt > 50.0 * aero.ft
    gsnorth = tasnorth + windn * airborne
    gseast = taseast + winde * airborne
    gs = jnp.where(airborne, jnp.sqrt(gsnorth * gsnorth + gseast * gseast),
                   ac.tas)
    trk = jnp.where(airborne,
                    jnp.degrees(jnp.arctan2(gseast, gsnorth)) % 360.0,
                    ac.hdg)
    return ac.replace(gsnorth=gsnorth, gseast=gseast, gs=gs, trk=trk)


def update_position(ac, pilot, simdt):
    """Explicit-Euler position integration (traffic.py:478-483).

    Altitude snaps to the pilot-commanded altitude once within capture range
    (``swaltsel`` False), exactly like the reference; lat/lon advance on the
    mean-radius sphere with the cos(lat) meridian-convergence factor.
    """
    alt = jnp.where(ac.swaltsel, ac.alt + ac.vs * simdt, pilot.alt)
    lat = ac.lat + jnp.degrees(simdt * ac.gsnorth / aero.Rearth)
    coslat = jnp.cos(jnp.radians(lat))
    lon = ac.lon + jnp.degrees(simdt * ac.gseast / coslat / aero.Rearth)
    return ac.replace(alt=alt, lat=lat, lon=lon, coslat=coslat)


def update_atmosphere(ac):
    """Refresh p/rho/T at current altitudes (traffic.py:389)."""
    p, rho, temp = aero.vatmos(ac.alt)
    return ac.replace(p=p, rho=rho, temp=temp)
