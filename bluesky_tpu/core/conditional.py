"""Deferred conditional commands: ATALT / ATSPD triggers.

Parity with the reference ``bluesky/traffic/conditional.py:13-129``: each
condition watches one aircraft's altitude or speed and fires a stored stack
command when the watched value crosses its target (sign change of
``target - actual`` between two evaluations, so overshoot can't miss).

TPU-first divergences:
* Conditions are evaluated at *chunk edges* from one host sample of the
  state arrays, not every 0.05 s step.  The sign-change predicate makes the
  trigger robust to the coarser sampling; the fire time quantizes to the
  chunk (<= 1 s in normal operation — the Simulation clamps the chunk
  ladder while conditions are pending so fast-forward can't defer a
  trigger by more than ~1 s of sim time).
* Aircraft slots are stable in this framework (delete never shifts
  indices), so the reference's index-decrement bookkeeping on deletion
  (conditional.py:118-129) reduces to dropping that slot's conditions.
"""
import numpy as np

ALT_TYPE, SPD_TYPE = 0, 1


class ConditionList:
    """Host-side condition table; tiny (human-issued), plain NumPy."""

    def __init__(self, sim):
        self.sim = sim
        self.idx = np.array([], dtype=np.int64)      # aircraft slot
        self.condtype = np.array([], dtype=np.int64)
        self.target = np.array([], dtype=np.float64)
        self.lastdif = np.array([], dtype=np.float64)
        self.cmd = []

    @property
    def ncond(self):
        return len(self.cmd)

    def permute(self, newslot):
        """Spatial shard re-bucketing moved aircraft between slots —
        follow them (slots stay stable between refreshes)."""
        if self.idx.size:
            self.idx = np.asarray(newslot)[self.idx].astype(np.int64)

    # ------------------------------------------------------------ commands
    def ataltcmd(self, acidx, targalt, cmdtxt):
        """acid ATALT alt cmd (conditional.py:51-54)."""
        actalt = float(self.sim.traf.state.ac.alt[acidx])
        self._add(acidx, ALT_TYPE, targalt, actalt, cmdtxt)
        return True

    def atspdcmd(self, acidx, targspd, cmdtxt):
        """acid ATSPD spd cmd (conditional.py:56-59).

        The watched value is CAS (matching the reference's update(), which
        compares against ``bs.traf.cas``; its add-time sample of ``tas`` is
        inconsistent with its own trigger test — we use CAS on both sides)."""
        actspd = float(self.sim.traf.state.ac.cas[acidx])
        self._add(acidx, SPD_TYPE, targspd, actspd, cmdtxt)
        return True

    def _add(self, acidx, icondtype, target, actual, cmdtxt):
        self.idx = np.append(self.idx, acidx)
        self.condtype = np.append(self.condtype, icondtype)
        self.target = np.append(self.target, target)
        self.lastdif = np.append(self.lastdif, target - actual)
        self.cmd.append(cmdtxt)

    # ------------------------------------------------------------- update
    def update(self):
        """Fire conditions whose watched value crossed the target since the
        last evaluation (conditional.py:25-49).  Called at chunk edges."""
        if self.ncond == 0:
            return
        ac = self.sim.traf.state.ac
        alt = np.asarray(ac.alt)[self.idx]
        cas = np.asarray(ac.cas)[self.idx]
        actual = np.where(self.condtype == ALT_TYPE, alt, cas)
        actdif = self.target - actual
        fire = np.where(actdif * self.lastdif <= 0.0)[0]
        self.lastdif = actdif
        if len(fire) == 0:
            return
        cmds = [self.cmd[i] for i in fire]
        self._delete(fire)
        for c in cmds:
            self.sim.stack.stack(c)

    def _delete(self, sel):
        keep = np.ones(self.ncond, dtype=bool)
        keep[sel] = False
        self.idx = self.idx[keep]
        self.condtype = self.condtype[keep]
        self.target = self.target[keep]
        self.lastdif = self.lastdif[keep]
        self.cmd = [c for c, k in zip(self.cmd, keep) if k]

    def delac(self, acidx):
        """Drop conditions of deleted aircraft; slots are stable so no
        index renumbering (cf. conditional.py:118-129)."""
        for i in np.atleast_1d(acidx):
            sel = np.where(self.idx == int(i))[0]
            if len(sel):
                self._delete(sel)

    def reset(self):
        self.__init__(self.sim)
