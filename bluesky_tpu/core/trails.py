"""Aircraft trails: line-segment position history for display.

Parity with the reference ``bluesky/traffic/trails.py:9-236``: per-aircraft
last-sample anchors, a growing host buffer of (lat0, lon0, lat1, lon1, time,
color) line pieces appended every ``dttrail`` seconds while active, per-
aircraft colors, CLEAR/background handling, and the TRAIL ON/OFF [dt] /
TRAIL acid color stack command.

TPU-first divergences:
* Sampling happens at chunk edges from the already-fetched host copy of
  lat/lon (the ACDATA screen sample), never inside the jitted step, so
  trails cost nothing on device.
* Segments for all due aircraft are appended as array blocks (the
  reference loops per aircraft, trails.py:95-115).
* Slots are stable; the per-aircraft anchors are fixed-size [nmax] arrays.
"""
import numpy as np

COLORLIST = {
    "BLUE": (0, 0, 255),
    "CYAN": (0, 255, 255),
    "RED": (255, 0, 0),
    "YELLOW": (255, 255, 0),
}


class Trails:
    def __init__(self, traf, dttrail=10.0):
        self.traf = traf
        self.active = False
        self.dt = dttrail
        self.tcol0 = 60.0                      # fade-to-old after [s]
        self.defcolor = COLORLIST["CYAN"]
        nmax = traf.nmax
        self.accolor = np.tile(np.asarray(self.defcolor, np.uint8),
                               (nmax, 1))     # [nmax,3]
        self.lastlat = np.zeros(nmax)
        self.lastlon = np.zeros(nmax)
        self.lasttim = np.zeros(nmax)
        # Pipelined edges skip the inactive-path anchor refresh (it
        # would force a telemetry fetch nobody consumes), so TRAIL ON
        # requests a one-shot re-anchor before the first segments.
        self._need_anchor = False
        self._clear_buffers()
        # Follow aircraft across spatial shard re-bucketings (the
        # per-slot anchors/colors are keyed by caller slot)
        traf.permute_hooks.append(self.permute_slots)

    def permute_slots(self, newslot):
        ns = np.asarray(newslot)
        inv = np.argsort(ns)                   # new slot -> old slot
        self.accolor = self.accolor[inv]
        self.lastlat = self.lastlat[inv]
        self.lastlon = self.lastlon[inv]
        self.lasttim = self.lasttim[inv]

    def _clear_buffers(self):
        # Foreground line pieces (streamed in ACDATA / drawn by a GUI)
        self.lat0 = np.array([])
        self.lon0 = np.array([])
        self.lat1 = np.array([])
        self.lon1 = np.array([])
        self.time = np.array([])
        self.col = np.zeros((0, 3), dtype=np.uint8)
        # Background copy (frozen picture on CLEAR, trails.py:156-175)
        self.bglat0 = np.array([])
        self.bglon0 = np.array([])
        self.bglat1 = np.array([])
        self.bglon1 = np.array([])
        self.bgtime = np.array([])
        self.bgcol = np.zeros((0, 3), dtype=np.uint8)
        # Segments added since the last ACDATA send (the stream sends
        # only deltas: screenio.py:216-222 newlat0.../clearnew)
        self.clearnew()

    def clearnew(self):
        self.newlat0 = np.array([])
        self.newlon0 = np.array([])
        self.newlat1 = np.array([])
        self.newlon1 = np.array([])

    # ------------------------------------------------------------ lifecycle
    def create(self, idx, lat, lon, t=0.0):
        """Anchor new aircraft at their spawn position (trails.py:64-69)."""
        idx = np.atleast_1d(idx)
        self.accolor[idx] = self.defcolor
        self.lastlat[idx] = np.atleast_1d(lat)
        self.lastlon[idx] = np.atleast_1d(lon)
        self.lasttim[idx] = t

    def delete(self, idx):
        # Stable slots: nothing to renumber; segments already in the buffer
        # stay visible like the reference's.
        pass

    def reset(self):
        self.active = False
        self._clear_buffers()
        self.lasttim[:] = 0.0

    # -------------------------------------------------------------- update
    def update(self, t, lat=None, lon=None, active=None):
        """Append segments for aircraft whose last anchor is > dt old.

        lat/lon/active: host samples of the state arrays (the pipelined
        chunk loop hands in the fused edge-telemetry pack — one bulk
        copy per edge); fetched from the live state only if not
        supplied.
        """
        active_mask = np.asarray(self.traf.state.ac.active) \
            if active is None else np.asarray(active)
        if lat is None:
            ac = self.traf.state.ac
            lat = np.asarray(ac.lat)
            lon = np.asarray(ac.lon)
        if not self.active or self._need_anchor:
            self.lastlat = np.array(lat, copy=True)
            self.lastlon = np.array(lon, copy=True)
            self.lasttim[:] = t
            self._need_anchor = False
            return
        # >= with an fp-slack so chunk edges spaced exactly dt apart (the
        # Simulation clamps the chunk to the trail resolution) still sample.
        due = active_mask & ((t - self.lasttim) >= self.dt - 1e-6)
        idxs = np.where(due)[0]
        if len(idxs) == 0:
            return
        self.lat0 = np.append(self.lat0, self.lastlat[idxs])
        self.lon0 = np.append(self.lon0, self.lastlon[idxs])
        self.lat1 = np.append(self.lat1, lat[idxs])
        self.lon1 = np.append(self.lon1, lon[idxs])
        self.time = np.append(self.time, np.full(len(idxs), t))
        self.col = np.concatenate([self.col, self.accolor[idxs]], axis=0)
        self.newlat0 = np.append(self.newlat0, self.lastlat[idxs])
        self.newlon0 = np.append(self.newlon0, self.lastlon[idxs])
        self.newlat1 = np.append(self.newlat1, lat[idxs])
        self.newlon1 = np.append(self.newlon1, lon[idxs])
        if len(self.newlat0) > 10000:
            # Backlog bound (headless run with no consumer, or a GUI
            # stalled behind): drop the OLDEST deltas, keeping the
            # just-appended batch so an active consumer still renders
            self.newlat0 = self.newlat0[-10000:]
            self.newlon0 = self.newlon0[-10000:]
            self.newlat1 = self.newlat1[-10000:]
            self.newlon1 = self.newlon1[-10000:]
        self.lastlat[idxs] = lat[idxs]
        self.lastlon[idxs] = lon[idxs]
        self.lasttim[idxs] = t

    # ------------------------------------------------------------- command
    def setTrails(self, *args):
        """TRAIL ON/OFF [dt] or TRAIL acid color (stack.py:734-739)."""
        if not args or args[0] is None:
            return True, f"TRAIL is {'ON' if self.active else 'OFF'}"
        a0 = args[0]
        if isinstance(a0, bool):
            if a0 and not self.active:
                self._need_anchor = True    # fresh anchors, no stale
                #                             segments from old positions
            self.active = a0
            if len(args) > 1 and args[1] is not None:
                try:
                    self.dt = float(args[1])
                except (TypeError, ValueError):
                    return False, f"{args[1]}: expected trail dt"
            return True
        if a0 == "CLEAR":
            self.clear()
            return True
        # TRAIL acid color
        try:
            idx = int(a0)
        except (TypeError, ValueError):
            return False, f"{a0}: expected ON/OFF/CLEAR or acid"
        if len(args) < 2 or str(args[1]).upper() not in COLORLIST:
            return False, "Usage: TRAIL acid BLUE/RED/CYAN/YELLOW"
        self.accolor[idx] = COLORLIST[str(args[1]).upper()]
        return True

    def clear(self):
        """Move current picture to the background buffer (trails.py CLEAR)."""
        self.bglat0 = np.append(self.bglat0, self.lat0)
        self.bglon0 = np.append(self.bglon0, self.lon0)
        self.bglat1 = np.append(self.bglat1, self.lat1)
        self.bglon1 = np.append(self.bglon1, self.lon1)
        self.bgtime = np.append(self.bgtime, self.time)
        self.bgcol = np.concatenate([self.bgcol, self.col], axis=0)
        n = len(self.bglat0)
        self.lat0 = np.array([])
        self.lon0 = np.array([])
        self.lat1 = np.array([])
        self.lon1 = np.array([])
        self.time = np.array([])
        self.col = np.zeros((0, 3), dtype=np.uint8)
        return n
