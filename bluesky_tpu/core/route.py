"""Host-side flight-plan (route) management writing dense device tables.

The reference keeps one Python ``Route`` object per aircraft with parallel
lists of waypoints and does all FMS lookups through it at sim rate
(route.py:15-1109).  Here the *editing* stays host-side (stack commands are
host events, arriving between step chunks) but the *data* lives in the dense
``RouteArrays`` tables of the state pytree that the jitted FMS consumes —
editing a route is a slot-row write, not an object mutation.

Implemented with reference semantics:
* waypoint ordering rules of ``Route.addwpt`` (orig at front, dest at end,
  normal waypoints before dest; route.py:472-614 simplified: navdb fuzzy
  position text resolution lives in stack/argparser)
* ``calcfp`` altitude-constraint propagation: for each waypoint, the next
  altitude constraint at/after it and the along-route distance to that
  constraint (route.py:983-1041) -> ``wptoalt``/``wpxtoalt``
* ``direct``: activate a waypoint and aim guidance at it (route.py:635-705)
* ``findact``: closest-ahead waypoint choice (route.py:1043-1075)
"""
import os
from typing import List, Optional

import numpy as np
import jax.numpy as jnp

from ..ops import aero

# Waypoint types (reference route.py wptype coding, dumpRoute legend)
WPT_LATLON, WPT_NAV, WPT_ORIG, WPT_DEST, WPT_CALC, WPT_RWY = range(6)


class HostRoute:
    """Host mirror of one aircraft's flight plan (names + arrays)."""

    def __init__(self):
        self.name: List[str] = []
        self.lat: List[float] = []
        self.lon: List[float] = []
        self.alt: List[float] = []      # [m], -999 = none
        self.spd: List[float] = []      # CAS m/s or Mach, -999 = none
        self.wtype: List[int] = []
        self.flyby: List[float] = []
        self.iactwp = -1
        # Landing chain fired for this plan (reference
        # Route.flag_landed_runway, route.py:741-775)
        self.flag_landed = False
        # Turn mode for subsequently added waypoints (reference
        # Route.swflyby, route.py:50; toggled by ADDWPT FLYBY/FLYOVER)
        self.swflyby = True

    @property
    def nwp(self):
        return len(self.name)


class RouteManager:
    """All host routes + synchronisation into the device RouteArrays."""

    def __init__(self, traf, wmax: int):
        self.traf = traf
        self.wmax = wmax
        self.routes = {}   # slot -> HostRoute
        # Deleted aircraft must not leave a stale plan for a reused slot
        # (the reference's route is a traf child cleared by the delete
        # cascade, trafficarrays.py:111-120).  The hook list survives
        # RouteManager replacement (sim reset), so register one shared
        # trampoline per Traffic that always targets its CURRENT manager.
        if getattr(traf, "_route_delete_hooked", None) is not traf:
            traf.delete_hooks.append(
                lambda idx, t=traf: t._route_mgr.drop_slots(idx)
                if getattr(t, "_route_mgr", None) else None)
            # Spatial shard refreshes move aircraft between caller
            # slots (stripe re-bucketing); host route plans are keyed
            # by slot and must move with them.
            traf.permute_hooks.append(
                lambda ns, t=traf: t._route_mgr.permute_slots(ns)
                if getattr(t, "_route_mgr", None) else None)
            traf._route_delete_hooked = traf
        traf._route_mgr = self

    def permute_slots(self, newslot):
        """Re-key the host plans after a spatial slot re-bucketing
        (``newslot[old] = new``); device route rows were already
        permuted with the state."""
        self.routes = {int(newslot[s]): r for s, r in self.routes.items()}

    def drop_slots(self, idx):
        """Clear the host plans of deleted slots and blank their device
        route rows (stale waypoint tables must not greet a reused slot)."""
        import numpy as np
        for i in np.atleast_1d(np.asarray(idx)):
            i = int(i)
            if i in self.routes:
                self.routes[i] = HostRoute()
                self.sync(i)          # blank the device row
                del self.routes[i]    # (sync would setdefault it back)

    def route(self, idx: int) -> HostRoute:
        return self.routes.setdefault(idx, HostRoute())

    def clear(self, idx: int):
        self.routes.pop(idx, None)

    # ------------------------------------------------------------- editing
    def addwpt(self, idx: int, name: str, lat: float, lon: float,
               alt: float = -999.0, spd: float = -999.0,
               wtype: int = WPT_LATLON, flyby: Optional[float] = None,
               afterwp: Optional[str] = None, as_dest: bool = False) -> int:
        """Insert a waypoint with the reference's ordering rules.

        ``as_dest`` marks a runway threshold added BY the DEST command
        (wtype WPT_RWY but destination placement: replace any trailing
        DEST/RWY, go last).  ``flyby=None`` takes the route's current
        turn mode (ADDWPT FLYBY/FLYOVER keyword, reference route.py:50).
        Returns the insertion index, or -1 on error (unknown afterwp).
        """
        r = self.route(idx)
        name = name.upper()
        if flyby is None:
            flyby = 1.0 if r.swflyby else 0.0

        if afterwp is not None:
            names = [n.upper() for n in r.name]
            if afterwp.upper() not in names:
                return -1
            wpidx = names.index(afterwp.upper()) + 1
        elif wtype == WPT_ORIG:
            # Origin goes at the front, replacing an existing origin
            if r.nwp > 0 and r.wtype[0] == WPT_ORIG:
                self._pop(r, 0)
            wpidx = 0
        elif wtype == WPT_DEST or as_dest:
            # Destination goes at the end, replacing an existing dest
            # (which may itself be a runway threshold)
            if r.nwp > 0 and r.wtype[-1] in (WPT_DEST, WPT_RWY):
                self._pop(r, r.nwp - 1)
            wpidx = r.nwp
        else:
            # Normal waypoints go before the destination if there is one
            # (a trailing runway threshold IS the destination — reference
            # setdestorig runway branch)
            wpidx = r.nwp - 1 \
                if (r.nwp > 0 and r.wtype[-1] in (WPT_DEST, WPT_RWY)) \
                else r.nwp

        if r.nwp >= self.wmax:
            raise RuntimeError(
                f"route full for slot {idx} (wmax={self.wmax}); raise wmax")

        r.name.insert(wpidx, name)
        r.lat.insert(wpidx, float(lat))
        r.lon.insert(wpidx, float(lon))
        r.alt.insert(wpidx, float(alt))
        r.spd.insert(wpidx, float(spd))
        r.wtype.insert(wpidx, int(wtype))
        r.flyby.insert(wpidx, float(flyby))
        if r.iactwp >= wpidx:
            r.iactwp += 1
        if r.iactwp < 0:
            r.iactwp = 0
        self.sync(idx)
        return wpidx

    @staticmethod
    def _pop(r: HostRoute, i: int):
        for lst in (r.name, r.lat, r.lon, r.alt, r.spd, r.wtype, r.flyby):
            del lst[i]
        if r.iactwp > i:
            r.iactwp -= 1

    def delrte(self, idx: int) -> bool:
        """DELRTE: drop the complete route incl. orig/dest
        (route.py delrte)."""
        self.clear(idx)
        self.sync(idx)
        return True

    def addwpt_before(self, idx: int, beforewp: str, name: str,
                      lat: float, lon: float,
                      alt: float = -999.0, spd: float = -999.0) -> int:
        """BEFORE beforewp ADDWPT (route.py beforeaddwptStack): insert a
        waypoint in front of a named one.  Returns index or -1."""
        r = self.route(idx)
        names = [n.upper() for n in r.name]
        if beforewp.upper() not in names:
            return -1
        if r.nwp >= self.wmax:
            raise RuntimeError(
                f"route full for slot {idx} (wmax={self.wmax}); raise wmax")
        wpidx = names.index(beforewp.upper())
        r.name.insert(wpidx, name.upper())
        r.lat.insert(wpidx, float(lat))
        r.lon.insert(wpidx, float(lon))
        r.alt.insert(wpidx, float(alt))
        r.spd.insert(wpidx, float(spd))
        r.wtype.insert(wpidx, WPT_LATLON)
        r.flyby.insert(wpidx, 1.0 if r.swflyby else 0.0)
        if r.iactwp >= wpidx:
            r.iactwp += 1
        self.sync(idx)
        return wpidx

    def atwpt(self, idx: int, wpname: str, what: Optional[str] = None,
              value=None):
        """AT wpname [DEL] SPD/ALT [val]: show/edit/delete constraints
        at a route waypoint (route.py atwptStack).

        Returns (ok, message or None)."""
        r = self.route(idx)
        names = [n.upper() for n in r.name]
        if wpname.upper() not in names:
            return False, f"{wpname} not in route"
        i = names.index(wpname.upper())
        if what is None:
            alttxt = "-----" if r.alt[i] < 0 else f"{r.alt[i]:.0f} m"
            spdtxt = "-----" if r.spd[i] < 0 else f"{r.spd[i]:.2f}"
            return True, f"{wpname}: alt {alttxt}, spd {spdtxt}"
        w = what.upper()
        if w.count("/") == 1:
            # acid AT wpname alt"/"spd — both constraints in one token
            # (reference route.py:344-375; "---" deletes a constraint).
            # Parse BOTH halves before mutating: a bad spd half must not
            # leave a half-applied, unsynced constraint.
            from ..utils.units import txt2alt, txt2spd
            alttxt, spdtxt = w.split("/")
            try:
                newalt = r.alt[i] if not alttxt else (
                    -999.0 if alttxt.count("-") > 1 else float(txt2alt(alttxt)))
                newspd = r.spd[i] if not spdtxt else (
                    -999.0 if spdtxt.count("-") > 1 else float(txt2spd(spdtxt)))
            except Exception as e:
                return False, f"Could not parse {what} as alt/spd ({e})"
            r.alt[i] = newalt
            r.spd[i] = newspd
            self.sync(idx)
            return True, None
        if w == "DEL":
            which = (str(value).upper() if value is not None else "BOTH")
            if which in ("ALT", "BOTH"):
                r.alt[i] = -999.0
            if which in ("SPD", "BOTH"):
                r.spd[i] = -999.0
        elif w == "ALT":
            if value is None:
                return False, "AT wpname ALT value"
            r.alt[i] = float(value)
        elif w == "SPD":
            if value is None:
                return False, "AT wpname SPD value"
            r.spd[i] = float(value)
        else:
            return False, f"AT: unknown argument {what}"
        self.sync(idx)   # sync recomputes calcfp's constraint tables
        return True, None

    def dumproute(self, idx: int, acid: str,
                  path: Optional[str] = None) -> str:
        """DUMPRTE: append the route table to <log_path>/routelog.txt
        (route.py dumpRoute)."""
        if path is None:
            from .. import settings
            path = settings.log_path
        os.makedirs(path, exist_ok=True)
        fname = os.path.join(path, "routelog.txt")
        r = self.route(idx)
        with open(fname, "a") as f:
            f.write(f"\nRoute {acid}:\n")
            f.write("(name, lat, lon, alt, spd, active)\n")
            for i in range(r.nwp):
                f.write(f"{r.name[i]}, {r.lat[i]:.6f}, {r.lon[i]:.6f}, "
                        f"{r.alt[i]:.1f}, {r.spd[i]:.2f}, "
                        f"{i == r.iactwp}\n")
            f.write("***\n")
        return fname

    def delwpt(self, idx: int, name: str) -> bool:
        r = self.route(idx)
        if name == "*":
            self.routes[idx] = HostRoute()
            self.sync(idx)
            return True
        names = [n.upper() for n in r.name]
        if name.upper() not in names:
            return False
        # reference deletes the LAST matching occurrence (route.py:816-821)
        i = len(names) - 1 - names[::-1].index(name.upper())
        self._pop(r, i)
        r.iactwp = min(r.iactwp, r.nwp - 1)
        self.sync(idx)
        return True

    def direct(self, idx: int, name: str) -> bool:
        """DIRECT: jump the active waypoint to ``name`` and point guidance at
        it (route.py:635-705, condensed: the VNAV re-trigger happens at the
        next FMS tick from the synced tables)."""
        r = self.route(idx)
        names = [n.upper() for n in r.name]
        if name.upper() not in names:
            return False
        r.iactwp = names.index(name.upper())
        self.sync(idx, point_active=True)
        return True

    def findact(self, idx: int) -> int:
        """Closest-ahead waypoint (route.py:1043-1075)."""
        r = self.route(idx)
        if r.nwp <= 0:
            return -1
        if r.nwp == 1:
            return 0
        st = self.traf.state
        aclat = float(st.ac.lat[idx])
        aclon = float(st.ac.lon[idx])
        coslat = float(st.ac.coslat[idx])
        trk = float(st.ac.trk[idx])
        tas = float(st.ac.tas[idx])
        bank = float(st.ac.bank[idx])

        dy = np.asarray(r.lat) - aclat
        dx = (np.asarray(r.lon) - aclon) * coslat
        dist2 = dx * dx + dy * dy
        iwpnear = max(r.iactwp, int(np.argmin(dist2)))
        if iwpnear + 1 < r.nwp:
            qdr = np.degrees(np.arctan2(dx[iwpnear], dy[iwpnear]))
            delhdg = abs((trk - qdr + 180.0) % 360.0 - 180.0)
            time_turn = max(0.01, tas) * np.radians(delhdg) \
                / (aero.g0 * np.tan(bank))
            time_straight = np.sqrt(dist2[iwpnear]) * 60.0 * aero.nm \
                / max(0.01, tas)
            if time_turn > time_straight:
                iwpnear += 1
        return iwpnear

    # --------------------------------------------------------------- sync
    def calcfp(self, r: HostRoute):
        """Altitude-constraint lookahead tables (route.py:983-1041)."""
        n = r.nwp
        wpdistto = np.zeros(n)          # [nm] distance from wp i-1 to i
        for i in range(n - 1):
            from ..core.traffic import _np_vatmos  # noqa: F401 (host helpers)
            wpdistto[i + 1] = _host_qdrdist_nm(r.lat[i], r.lon[i],
                                               r.lat[i + 1], r.lon[i + 1])
        wptoalt = np.full(n, -999.0)
        wpxtoalt = np.ones(n)
        toalt, xtoalt = -999.0, 0.0
        for i in range(n - 1, -1, -1):
            if r.wtype[i] == WPT_DEST:
                toalt, xtoalt = 0.0, 0.0
            elif r.alt[i] >= 0:
                toalt, xtoalt = r.alt[i], 0.0
            else:
                xtoalt = xtoalt + wpdistto[i + 1] * aero.nm if i != n - 1 \
                    else 0.0
            wptoalt[i] = toalt
            wpxtoalt[i] = xtoalt
        return wptoalt, wpxtoalt

    def runway_final_slots(self):
        """Slots whose plan ends at a runway waypoint and whose landing
        chain has not fired — the candidates for _check_runway_landings."""
        return [(s, r) for s, r in self.routes.items()
                if r.nwp > 0 and r.wtype[-1] == WPT_RWY
                and not r.flag_landed]

    def sync(self, idx: int, point_active: bool = False):
        """Write one slot's host route into the device tables."""
        self.traf.flush()
        r = self.route(idx)
        st = self.traf.state
        rt = st.route
        W = self.wmax
        n = r.nwp

        def row(vals, fill):
            out = np.full(W, fill)
            out[:n] = vals
            return out

        wptoalt, wpxtoalt = self.calcfp(r)
        i = idx
        dt = rt.wplat.dtype
        rt = rt.replace(
            wplat=rt.wplat.at[i].set(jnp.asarray(row(r.lat, 89.99), dt)),
            wplon=rt.wplon.at[i].set(jnp.asarray(row(r.lon, 0.0), dt)),
            wpalt=rt.wpalt.at[i].set(jnp.asarray(row(r.alt, -999.0), dt)),
            wpspd=rt.wpspd.at[i].set(jnp.asarray(row(r.spd, -999.0), dt)),
            wpflyby=rt.wpflyby.at[i].set(jnp.asarray(row(r.flyby, 1.0), dt)),
            wptoalt=rt.wptoalt.at[i].set(jnp.asarray(row(wptoalt, -999.0), dt)),
            wpxtoalt=rt.wpxtoalt.at[i].set(jnp.asarray(row(wpxtoalt, 0.0), dt)),
            nwp=rt.nwp.at[i].set(n),
            iactwp=rt.iactwp.at[i].set(r.iactwp))
        st = st.replace(route=rt)

        if point_active and 0 <= r.iactwp < n:
            k = r.iactwp
            actwp = st.actwp
            ac = st.ac
            st = st.replace(
                actwp=actwp.replace(
                    lat=actwp.lat.at[i].set(r.lat[k]),
                    lon=actwp.lon.at[i].set(r.lon[k]),
                    nextaltco=actwp.nextaltco.at[i].set(
                        r.alt[k] if r.alt[k] >= 0 else float(actwp.nextaltco[i])),
                    spd=actwp.spd.at[i].set(r.spd[k]),
                    flyby=actwp.flyby.at[i].set(r.flyby[k]),
                    xtoalt=actwp.xtoalt.at[i].set(float(wpxtoalt[k]))),
                ac=ac.replace(swlnav=ac.swlnav.at[i].set(True)))
        self.traf.state = st


def _host_qdrdist_nm(lat1, lon1, lat2, lon2):
    """Host float64 haversine distance [nm] (same math as ops/geo.qdrdist)."""
    a = 6378137.0
    b = 6356752.314245

    def rw(latd):
        la = np.radians(latd)
        cl, sl = np.cos(la), np.sin(la)
        an, bn = a * a * cl, b * b * sl
        ad, bd = a * cl, b * sl
        return np.sqrt((an * an + bn * bn) / (ad * ad + bd * bd))

    if lat1 * lat2 >= 0:
        r = rw(0.5 * (lat1 + lat2))
    else:
        r = 0.5 * (abs(lat1) * (rw(lat1) + a) + abs(lat2) * (rw(lat2) + a)) \
            / (abs(lat1) + abs(lat2))
    f1, f2 = np.radians(lat1), np.radians(lat2)
    g1, g2 = np.radians(lon1), np.radians(lon2)
    h = np.sin(0.5 * (f2 - f1)) ** 2 \
        + np.cos(f1) * np.cos(f2) * np.sin(0.5 * (g2 - g1)) ** 2
    return 2.0 * r * np.arctan2(np.sqrt(h), np.sqrt(1 - h)) / 1852.0
