"""FMS / autopilot guidance, fully vectorized over the aircraft axis.

Parity with the reference ``bluesky/traffic/autopilot.py`` + the waypoint-
reached predicate of ``activewpdata.py`` + the waypoint-advance semantics of
``Route.getnextwp`` (route.py:741-800).  The reference interleaves a scalar
per-aircraft Python loop (waypoint switching, autopilot.py:71-137, scalar
``ComputeVNAV`` autopilot.py:207-304) with vectorized continuous guidance
(autopilot.py:144-204).  That loop is unusable under jit, so here:

* Flight plans are dense ``[N, W]`` waypoint tables (core/state.RouteArrays)
  with a per-aircraft active index; altitude-constraint lookahead
  (``wptoalt/wpxtoalt``, computed by ``Route.calcfp`` in the reference) is
  precomputed host-side at route-edit time.
* Waypoint advance is a masked gather: ``reached`` aircraft bump their index
  and pull the next row out of the tables with ``take_along_axis``.
* ``ComputeVNAV``'s three branches (descend-late / climb-now / level) become
  a ``jnp.where`` lattice evaluated for switching aircraft only.

Behavioural notes kept faithful to the reference:
* ComputeVNAV's writes to ``actwp.vs``/``ap.alt`` are clobbered by the
  continuous-guidance block in the same update (autopilot.py:171-185 runs
  after the loop, unconditionally) — so only its nextaltco/xtoalt/dist2vs
  outputs are durable, and that is what we compute.
* The runway-landing auto-delete (route.py:744-776) is host-side stack
  business and is handled by the host route manager, not here.
"""
import jax.numpy as jnp

from ..ops import aero, geo
from .state import SimState

# Default descent steepness: 3000 ft per 10 nm (reference autopilot.py:21)
STEEPNESS = 3000.0 * aero.ft / (10.0 * aero.nm)
FMS_DT = 1.01  # [s] FMS scheduling interval (reference autopilot.py:18)


def degto180(angle):
    """Wrap angle to (-180, 180] (reference tools/misc.py degto180)."""
    return (angle + 180.0) % 360.0 - 180.0


def calcturn(tas, bank, wpqdr, next_wpqdr):
    """Turn-anticipation distance and turn radius (activewpdata.py:57-66)."""
    turnrad = tas * tas / (jnp.maximum(0.01, jnp.tan(bank)) * aero.g0)
    turndist = jnp.abs(
        turnrad * jnp.tan(jnp.radians(0.5 * jnp.abs(
            degto180(wpqdr % 360.0 - next_wpqdr % 360.0)))))
    return turndist, turnrad


def update_fms(state: SimState) -> SimState:
    """The dt-gated FMS update: waypoint switching + continuous guidance.

    Mirrors Autopilot.update's gated body (autopilot.py:61-199).  Call only
    when the FMS timer fires; ``update_continuous`` runs every step.
    """
    ac, actwp, ap, route = state.ac, state.actwp, state.ap, state.route

    # --- LNAV geometry to the current active waypoint -----------------------
    qdr, distnm = geo.qdrdist(ac.lat, ac.lon, actwp.lat, actwp.lon)
    dist = distnm * aero.nm

    # --- Waypoint-reached predicate (activewpdata.Reached, :31-55) ----------
    next_qdr_eff = jnp.where(actwp.next_qdr < -900.0, qdr, actwp.next_qdr)
    turndist_r, turnrad = calcturn(ac.tas, ac.bank, qdr, next_qdr_eff)
    # flyby scales both outputs in the reference (tuple * array broadcast)
    turndist_r = actwp.flyby * turndist_r
    turnrad = actwp.flyby * turnrad

    away = jnp.abs(degto180(ac.trk % 360.0 - qdr % 360.0)) > 90.0
    incircle = dist < turnrad * 1.01
    circling = away & incircle
    reached = ac.swlnav & ((dist < turndist_r) | circling) & ac.active

    # --- Advance to next waypoint for reached aircraft (masked gather) ------
    # Route.getnextwp semantics (route.py:778-800): lnavon iff another
    # waypoint exists; the index saturates at the last waypoint.
    lnavon = route.iactwp + 1 < route.nwp
    iact_new = jnp.where(reached & lnavon, route.iactwp + 1, route.iactwp)

    # ONE fused [N, W, 7] gather instead of 7 per-table gathers — TPU
    # gathers serialize per index, so sharing the index vector across
    # the row-aligned tables is ~7x cheaper.
    tables = jnp.stack([route.wplat, route.wplon, route.wpalt,
                        route.wpspd, route.wpflyby, route.wptoalt,
                        route.wpxtoalt], axis=-1)        # [N, W, 7]
    safe = jnp.clip(iact_new, 0, route.wplat.shape[1] - 1)
    g = jnp.take_along_axis(tables, safe[:, None, None], axis=1)[:, 0]
    (wplat, wplon, wpalt, wpspd, wpflyby, wptoalt,
     wpxtoalt) = [g[:, i] for i in range(7)]
    # next leg bearing: from new wp to the one after (route.getnextqdr)
    have_next = iact_new + 1 < route.nwp
    safe2 = jnp.clip(iact_new + 1, 0, route.wplat.shape[1] - 1)
    g2 = jnp.take_along_axis(tables[:, :, :2], safe2[:, None, None],
                             axis=1)[:, 0]
    nxtlat, nxtlon = g2[:, 0], g2[:, 1]
    legqdr, _ = geo.qdrdist(wplat, wplon, nxtlat, nxtlon)
    next_qdr_new = jnp.where(have_next, legqdr, -999.0)

    # Save the speed constraint of the waypoint we are passing: VNAV speeds
    # are FROM-speeds (autopilot.py:73-78)
    oldspd = actwp.spd

    swlnav = jnp.where(reached, ac.swlnav & lnavon, ac.swlnav)
    swvnav = ac.swvnav & swlnav

    new_wplat = jnp.where(reached, wplat, actwp.lat)
    new_wplon = jnp.where(reached, wplon, actwp.lon)
    new_flyby = jnp.where(reached, wpflyby, actwp.flyby)
    new_nextaltco = jnp.where(reached & (wpalt >= -0.01), wpalt,
                              actwp.nextaltco)
    new_xtoalt = jnp.where(reached, wpxtoalt, actwp.xtoalt)

    # Speed constraint with crossover-altitude conversion (autopilot.py:99-113)
    spd_valid = (wpspd > -990.0) & swlnav & swvnav
    spd_conv = jnp.where(
        ac.abco & (wpspd > 1.0), aero.vcas2mach(wpspd, ac.alt),
        jnp.where(ac.belco & (0.0 < wpspd) & (wpspd <= 1.0),
                  aero.vmach2cas(wpspd, ac.alt), wpspd))
    new_wpspd = jnp.where(reached,
                          jnp.where(spd_valid, spd_conv, -999.0), actwp.spd)

    # VNAV from-speed becomes the selected speed while passing (ap.py:118-119)
    selspd = jnp.where(reached & swvnav & (oldspd > 0.0), oldspd, ac.selspd)

    # Recompute qdr/turndist for the new active waypoint (autopilot.py:121-134)
    qdr_new, _ = geo.qdrdist(ac.lat, ac.lon, new_wplat, new_wplon)
    qdr = jnp.where(reached, qdr_new, qdr)
    local_next_qdr = jnp.where(next_qdr_new < -900.0, qdr, next_qdr_new)
    turndist_new, _ = calcturn(ac.tas, ac.bank, qdr, local_next_qdr)
    new_turndist = jnp.where(reached, turndist_new, actwp.turndist)
    new_next_qdr = jnp.where(reached, next_qdr_new, actwp.next_qdr)

    # --- ComputeVNAV for switching aircraft (autopilot.py:207-304) ----------
    # Durable outputs only: nextaltco, xtoalt (already set), dist2vs.
    toalt = wptoalt
    novnav = (toalt < 0.0) | ~swvnav
    descend = ac.alt > toalt + 10.0 * aero.ft
    climb = ac.alt < toalt - 10.0 * aero.ft

    nextaltco_d = jnp.minimum(ac.alt, toalt + wpxtoalt * STEEPNESS)
    dist2vs_d = new_turndist + jnp.abs(ac.alt - nextaltco_d) / STEEPNESS

    vnav_nextaltco = jnp.where(descend, nextaltco_d,
                               jnp.where(climb, toalt, new_nextaltco))
    vnav_dist2vs = jnp.where(descend, dist2vs_d,
                             jnp.where(climb, 99999.0 * aero.nm, -999.0))
    vnav_dist2vs = jnp.where(novnav, -999.0, vnav_dist2vs)
    # With VNAV off the constraint stays as set above; with it on and a
    # climb/descent ahead, dial in the computed constraint altitude.
    new_nextaltco = jnp.where(reached & ~novnav & (descend | climb),
                              vnav_nextaltco, new_nextaltco)
    dist2vs = jnp.where(reached, vnav_dist2vs, ap.dist2vs)

    actwp = actwp.replace(lat=new_wplat, lon=new_wplon, flyby=new_flyby,
                          nextaltco=new_nextaltco, xtoalt=new_xtoalt,
                          spd=new_wpspd, turndist=new_turndist,
                          next_qdr=new_next_qdr)
    route = route.replace(iactwp=iact_new)

    # --- Continuous FMS guidance (autopilot.py:144-199) ---------------------
    dy = actwp.lat - ac.lat
    dx = (actwp.lon - ac.lon) * ac.coslat
    dist2wp = 60.0 * aero.nm * jnp.sqrt(dx * dx + dy * dy)

    startdescent = (dist2wp < dist2vs) | (actwp.nextaltco > ac.alt)
    swvnavvs = swvnav & jnp.where(swlnav, startdescent,
                                  dist <= jnp.maximum(185.2, actwp.turndist))

    t2go2alt = jnp.maximum(0.0, dist2wp + actwp.xtoalt - actwp.turndist) \
        / jnp.maximum(0.5, ac.gs)
    actwp_vs = jnp.maximum(STEEPNESS * ac.gs,
                           jnp.abs(actwp.nextaltco - ac.alt)
                           / jnp.maximum(1.0, t2go2alt))
    actwp = actwp.replace(vs=actwp_vs)

    vnavvs = jnp.where(swvnavvs, actwp_vs, ap.vnavvs)
    selvs_eff = jnp.where(jnp.abs(ac.selvs) > 0.1, ac.selvs, ac.apvsdef)
    ap_vs = jnp.where(swvnavvs, vnavvs, selvs_eff)
    ap_alt = jnp.where(swvnavvs, actwp.nextaltco, ac.selalt)
    selalt = jnp.where(swvnavvs, actwp.nextaltco, ac.selalt)

    ap_trk = jnp.where(swlnav, qdr, ap.trk)

    # FMS speed guidance with deceleration-distance anticipation
    # (autopilot.py:190-199)
    nexttas = aero.vcasormach2tas(actwp.spd, ac.alt)
    tasdiff = nexttas - ac.tas
    dtspdchg = jnp.abs(tasdiff) / jnp.maximum(0.01, jnp.abs(ac.ax))
    dxspdchg = (0.5 * jnp.sign(tasdiff) * jnp.abs(ac.ax) * dtspdchg * dtspdchg
                + ac.tas * dtspdchg)
    usespdcon = (dist2wp < dxspdchg) & (actwp.spd > -990.0) & swvnav
    selspd = jnp.where(usespdcon, actwp.spd, selspd)

    ac = ac.replace(swlnav=swlnav, swvnav=swvnav, selspd=selspd,
                    selalt=selalt)
    ap = ap.replace(trk=ap_trk, alt=ap_alt, vs=ap_vs, vnavvs=vnavvs,
                    swvnavvs=swvnavvs, dist2vs=dist2vs)
    return state.replace(ac=ac, actwp=actwp, ap=ap, route=route)


def update_continuous(state: SimState) -> SimState:
    """Per-step TAS command from the selected CAS/Mach (autopilot.py:202-203)."""
    ap_tas = aero.vcasormach2tas(state.ac.selspd, state.ac.alt)
    return state.replace(ap=state.ap.replace(tas=ap_tas))
