"""The simulation step: one fused, jittable state -> state function.

Mirrors the reference hot loop ``Traffic.update`` (traffic.py:383-423) and
its caller ``Simulation.step`` (simulation/qtgl/simulation.py:62-128), with
the reference's time-staggered scheduling (FMS at ~1.01 s, ASAS at 1 s,
kinematics every simdt=0.05 s) reproduced *inside* jit via ``lax.cond`` on
device clocks — so a whole chunk of steps runs as one ``lax.scan`` with a
single host sync per chunk instead of the reference's per-step Python
dispatch.

Pipeline order per step (identical to traffic.py:383-423, OpenAP flavour):
  atmosphere -> ADS-B -> FMS (gated) -> ASAS CD&R (gated) -> AP/ASAS
  arbitration -> performance update -> envelope limits -> airspeed ->
  groundspeed (wind) -> position -> turbulence
"""
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from . import asas as asasmod
from . import autopilot, kinematics, noise, perf as perfmod, pilot, wind as windmod
from .asas import AsasConfig
from .noise import NoiseConfig
from .state import SimState


class SimConfig(NamedTuple):
    """Static simulation configuration (hashable -> jit-static).

    Changing a field recompiles the step (cached per value) — these change
    at stack-command cadence, not step cadence.
    """
    simdt: float = 0.05          # [s] (reference simulation.py:15)
    fms_dt: float = autopilot.FMS_DT
    asas: AsasConfig = AsasConfig()
    noise: NoiseConfig = NoiseConfig()
    use_wind: bool = False
    # CD&R backend: 'dense' materialises [N,N] (exact reference parity,
    # fine to ~16k AC); 'tiled' streams [cd_block]² tiles with a [N,K]
    # partner table — required for the 100k north star (ops/cd_tiled.py);
    # 'pallas' is the tiled scheme as a hand-written TPU kernel
    # (ops/cd_pallas.py, TPU-only); 'sparse' is the segment-scheduled
    # kernel with the stripe sort (ops/cd_sched.py, TPU-only) — the
    # fastest large-N path for spread-out fleets, exact-equal results.
    cd_backend: str = "dense"
    cd_block: int = 512
    # Device mesh for the Pallas backends' shard_map row split (the lax
    # and dense backends shard via GSPMD from state shardings alone and
    # ignore this).  A jax.sharding.Mesh is hashable, so the config
    # stays jit-static; parallel.sharding.sharded_step_fn fills it in.
    cd_mesh: object = None
    cd_mesh_axis: str = "ac"
    # Multi-chip decomposition of the sparse backend on that mesh:
    # 'replicate' = interleaved row blocks vs replicated O(N) columns
    # (the round-4 scheme, ~200x ceiling as D grows); 'spatial' =
    # device-owned latitude stripes with conservative halo exchange —
    # O(N/D) state/schedule/sort per device, O(halo) wire per interval
    # (docs/PERF_ANALYSIS.md §multi-chip).  Spatial requires the
    # stripe-bucketed caller layout kept by the spatial sort refresh
    # (core/asas.refresh_spatial_shard / the SHARD stack command).
    cd_shard_mode: str = "replicate"
    # Halo width in 256-wide blocks each side of a device's stripe
    # range (0 = one full neighbour device, always covering; smaller
    # values cut the boundary exchange and are validated against the
    # exact reach bound + drift margin at every refresh).
    cd_halo_blocks: int = 0
    # 2-D tile decomposition ('tiles' shard mode): (R, C) shape of the
    # ('lat', 'lon') device mesh, and the per-canonical-offset halo
    # slab budgets pinned by the tile refresh (() = unpinned, whole
    # neighbour tiles).  Tuples, so the config stays hashable/static.
    cd_tile_shape: tuple = ()
    cd_tile_budgets: tuple = ()
    # Differentiable mode (bluesky_tpu/diff/): a diff.smooth.SmoothConfig
    # swaps the hard gates for the documented relaxations (conflict
    # sigmoid, softmin resolver reductions, straight-through clamps,
    # stop-gradiented RNG draws) so jax.grad through run_steps carries
    # useful gradients.  None — the default, and the ONLY value the
    # serving path ever sets — takes every original code path at trace
    # time: bit-identical to the pre-relaxation step (tests/test_diff.py
    # pins this).  A NamedTuple of floats, so the config stays hashable.
    smooth: object = None
    # In-scan telemetry (obs/scanstats.py): fold per-step device-side
    # stats through the chunk-scan carry and emit them once per chunk
    # as extra non-donated outputs next to EdgeTelemetry.  False — the
    # default — takes the original scan code path at trace time
    # (bit-identical HLO, pinned by obs_smoke's parity hash); True adds
    # pure carry folds and ZERO host syncs or in-scan collectives
    # (tests/test_hlo_collectives.py pins the collective budget).
    scanstats: bool = False
    # In-scan sort refresh (sparse backend): fold the stripe re-sort —
    # and, in spatial mode, the caller-slot re-bucketing — into the
    # chunk scan as a scalar ``lax.cond`` on the sort_every*dtasas
    # cadence, so chunk edges carry ZERO host refresh work and chunk
    # length stops mattering (the 20-step interactive gap of
    # BENCH_CHUNK_SWEEP.json).  The composed caller-slot bijection and
    # a structured guard word ride the RefreshPack edge output; the
    # host applies the permutation to ids/routes once per chunk and
    # trips the fallback-to-replicate path on guard violations.  False
    # — the default — takes the original scan code path at trace time
    # (bit-identical HLO, same contract as ``scanstats``).
    inscan_refresh: bool = False
    # SDC-defense state fingerprint (obs/fingerprint.py): fold a 32-bit
    # bit-pattern witness of the stepped state through the chunk-scan
    # carry and emit it once per chunk next to EdgeTelemetry, so the
    # serving layer can compare hedge-duplicate / shadow-audit / voted
    # re-executions of the same piece bit-for-bit.  False — the default
    # — takes the original scan code path at trace time (bit-identical
    # HLO, the scanstats contract); True adds pure bitwise carry folds
    # with ZERO host syncs and ZERO in-scan collectives.
    fingerprint: bool = False


def step(state: SimState, cfg: SimConfig) -> SimState:
    """Advance the simulation by one simdt. Pure; jit/scan/donate-friendly."""
    simdt = jnp.asarray(cfg.simdt, state.simt.dtype)
    simt = state.simt

    # ---------- Atmosphere (traffic.py:389) ----------
    state = state.replace(ac=kinematics.update_atmosphere(state.ac))

    # ---------- ADS-B broadcast model (traffic.py:392) ----------
    if cfg.noise.turb_active or cfg.noise.adsb_transnoise:
        rng, k_adsb, k_turb = jax.random.split(state.rng, 3)
    else:
        # no noise consumer this step: skip the PRNG split entirely
        # (the key is never read below; the stream stays untouched so
        # toggling noise mid-run starts from the same key)
        rng = k_adsb = k_turb = state.rng
    state = state.replace(
        rng=rng,
        adsb=noise.adsb_update(state.adsb, state.ac, k_adsb, simt, cfg.noise,
                               smooth=cfg.smooth))

    # ---------- FMS / autopilot (traffic.py:395), gated at fms_dt ----------
    fms_due = (state.fms_t0 + cfg.fms_dt < simt) | (simt < state.fms_t0) \
        | (simt < cfg.fms_dt)

    def run_fms(s):
        return autopilot.update_fms(s).replace(fms_t0=simt)

    state = jax.lax.cond(fms_due, run_fms, lambda s: s, state)
    state = autopilot.update_continuous(state)

    # ---------- ASAS CD&R (traffic.py:396), gated at dtasas ----------
    if cfg.asas.swasas:
        if cfg.cd_backend not in ("dense", "tiled", "pallas", "sparse"):
            raise ValueError(
                f"Unknown SimConfig.cd_backend {cfg.cd_backend!r}; "
                "expected 'dense', 'tiled', 'pallas' or 'sparse'.")
        if cfg.smooth is not None and cfg.cd_backend != "dense":
            raise ValueError(
                "SimConfig.smooth (differentiable mode) relaxes the "
                "dense CD&R path only: the tiled/pallas/sparse kernels "
                "carry integer partner tables that do not differentiate."
                "  Use cd_backend='dense' (diff workloads run small-N).")
        if cfg.cd_shard_mode not in ("replicate", "spatial", "tiles"):
            raise ValueError(
                f"Unknown SimConfig.cd_shard_mode {cfg.cd_shard_mode!r}; "
                "expected 'replicate', 'spatial' or 'tiles'.")
        if cfg.cd_shard_mode in ("spatial", "tiles") \
                and cfg.cd_backend != "sparse":
            raise ValueError(
                f"cd_shard_mode='{cfg.cd_shard_mode}' is the sparse "
                "backend's domain decomposition (stripes/tiles are a "
                "property of the sorted schedule); use "
                "cd_backend='sparse'")
        if cfg.cd_shard_mode == "tiles" and (
                not cfg.cd_tile_shape or len(cfg.cd_tile_shape) != 2):
            raise ValueError(
                "cd_shard_mode='tiles' needs cd_tile_shape=(R, C) — "
                "set it via Simulation.set_shard / SHARD TILE RxC")
        if cfg.cd_backend == "dense" and state.asas.resopairs.size == 0:
            raise ValueError(
                "State was allocated with pair_matrix=False (no [N,N] "
                "resopairs) but SimConfig.cd_backend is "
                f"'{cfg.cd_backend}'. Use SimConfig(cd_backend='tiled') or "
                "allocate Traffic(pair_matrix=True).")
        if cfg.cd_backend != "dense" and cfg.asas.reso_on:
            rm = cfg.asas.reso_method.upper()
            if rm not in ("MVP", "EBY", "SWARM", "SSD"):
                raise ValueError(
                    f"Unknown resolver {cfg.asas.reso_method!r}; every "
                    "backend carries MVP/EBY (pair sums), SWARM "
                    "(neighbour sums) and SSD (partner-table VOs) — "
                    "reference asas.py:41-55 keeps CD and CR orthogonal.")
        asas_due = simt >= state.asas_tnext

        def run_asas(s):
            if cfg.cd_backend in ("tiled", "pallas", "sparse"):
                impl = asasmod.impl_for_backend(cfg.cd_backend)
                s2, _cd = asasmod.update_tiled(
                    s, cfg.asas, block=cfg.cd_block, impl=impl,
                    mesh=cfg.cd_mesh, mesh_axis=cfg.cd_mesh_axis,
                    shard_mode=cfg.cd_shard_mode,
                    halo_blocks=cfg.cd_halo_blocks,
                    tile_shape=cfg.cd_tile_shape or None,
                    tile_budgets=cfg.cd_tile_budgets)
            else:
                s2, _cd = asasmod.update(s, cfg.asas, smooth=cfg.smooth)
            return s2.replace(
                asas_tnext=s.asas_tnext
                + jnp.asarray(cfg.asas.dtasas, s.asas_tnext.dtype))

        state = jax.lax.cond(asas_due, run_asas, lambda s: s, state)

    # ---------- Pilot arbitration (traffic.py:397) ----------
    if cfg.use_wind:
        windn, winde = windmod.getdata(state.wind, state.ac.lat,
                                       state.ac.lon, state.ac.alt)
    else:
        windn = winde = None
    state = pilot.ap_or_asas(state, windn, winde)

    # ---------- Performance model update (traffic.py:399-401) ----------
    new_perf, bank = perfmod.update(state.perf, state.ac.tas, state.ac.vs,
                                    state.ac.alt)
    state = state.replace(perf=new_perf, ac=state.ac.replace(bank=bank))

    # ---------- Envelope limits (traffic.py:404) ----------
    state = pilot.apply_limits(state, smooth=cfg.smooth)

    # ---------- Kinematics (traffic.py:406-409) ----------
    accel = perfmod.acceleration(state.perf.phase)
    ac = kinematics.update_airspeed(state.ac, state.pilot, accel, simdt,
                                    smooth=cfg.smooth)
    ac = kinematics.update_groundspeed(ac, windn, winde)
    ac = kinematics.update_position(ac, state.pilot, simdt)

    # ---------- Turbulence (traffic.py:416) ----------
    ac = noise.turbulence_woosh(ac, k_turb, simdt, cfg.noise,
                                smooth=cfg.smooth)

    # Freeze padding slots: inactive rows keep their values bit-exactly so
    # garbage can never leak into streams/logs.
    live = ac.active
    frz = lambda new, old: jnp.where(live, new, old)
    ac = ac.replace(
        lat=frz(ac.lat, state.ac.lat), lon=frz(ac.lon, state.ac.lon),
        alt=frz(ac.alt, state.ac.alt), hdg=frz(ac.hdg, state.ac.hdg),
        trk=frz(ac.trk, state.ac.trk), tas=frz(ac.tas, state.ac.tas),
        gs=frz(ac.gs, state.ac.gs), vs=frz(ac.vs, state.ac.vs))

    return state.replace(ac=ac, simt=simt + simdt)


# ---------------------------------------------------------- in-scan refresh
# The sparse backend's spatial-sort refresh folded into the chunk scan
# (SimConfig.inscan_refresh).  The refresh-due gate is a scalar
# ``lax.cond`` on the sort_every*dtasas cadence — the same hoisted-gate
# idiom as the worlds conds — invoking the already-jitted refresh
# bodies in core/asas.py; the carry accumulates the RefreshPack below.


def inscan_refresh_active(cfg: SimConfig) -> bool:
    """True when this config folds the sort refresh into the scan: the
    flag is on AND the backend is 'sparse' (the tiled/pallas Morton
    refresh stays host-called — its argsort has no in-scan body) AND
    ASAS runs at all.  Static: callers pivot output arity on it."""
    return bool(cfg.inscan_refresh and cfg.asas.swasas
                and cfg.cd_backend == "sparse")


class RefreshPack(NamedTuple):
    """In-scan refresh carry AND chunk-edge output (non-donated, rides
    the EdgeTelemetry pull).  Everything the host needs to retire a
    chunk's refreshes without having run any of them:

    * ``sort_t``: sim time of the most recent refresh (same dtype as
      ``state.simt``; -1 = never).  The host threads it into the NEXT
      dispatch as ``sort_t0`` — as the raw device scalar in the
      pipelined loop, so chaining costs zero host syncs.
    * ``count``: int32 refreshes fired inside this chunk.
    * ``guard``: int32 structured guard word, OR of bit 1 (spatial
      stripe-occupancy overflow), bit 2 (halo-coverage / tile-budget
      violation) and bit 4 (tile-occupancy overflow).
      A violating refresh is SKIPPED on device (the stale sort stays
      exact, only looser) and the host trips the fallback-to-replicate
      path at the edge — never silently stepping a broken layout.
    * ``newslot``: the composed old-caller -> new-caller slot bijection
      across every in-chunk spatial refresh ([n] int32; empty [0] when
      not spatial — the mode is jit-static so the pytree is fixed per
      config key).  Applied to host-side objects (ids/routes/trails via
      ``Traffic.apply_slot_permutation``) exactly once per chunk.
    """
    sort_t: jnp.ndarray
    count: jnp.ndarray
    guard: jnp.ndarray
    newslot: jnp.ndarray


def _refresh_init(state: SimState, cfg: SimConfig, sort_t0,
                  worlds: bool = False) -> RefreshPack:
    """Chunk-start RefreshPack: ``sort_t0`` is the host's last-refresh
    sim time (scalar, [W] for worlds; None = never refreshed)."""
    if worlds:
        nw = state.simt.shape[0]
        if sort_t0 is None:
            sort_t0 = jnp.full((nw,), -1.0, state.simt.dtype)
        zero = jnp.zeros((nw,), jnp.int32)
    else:
        if sort_t0 is None:
            sort_t0 = jnp.full((), -1.0, state.simt.dtype)
        zero = jnp.zeros((), jnp.int32)
    spatial = (not worlds) and cfg.cd_shard_mode in ("spatial", "tiles")
    n = state.ac.lat.shape[-1]
    newslot = (jnp.arange(n, dtype=jnp.int32) if spatial
               else jnp.zeros((0,), jnp.int32))
    return RefreshPack(
        sort_t=jnp.asarray(sort_t0, state.simt.dtype), count=zero,
        guard=zero, newslot=newslot)


def _refresh_gate(s: SimState, rc: RefreshPack, cfg: SimConfig):
    """One scan-body iteration of the refresh schedule: fire the sparse
    (or spatial) refresh when the cadence is due, BEFORE the step — the
    same order as the host's pre-dispatch refresh.  Returns the
    (possibly refreshed) state and updated carry."""
    period = jnp.asarray(float(cfg.asas.sort_every * cfg.asas.dtasas),
                         s.simt.dtype)
    spatial = cfg.cd_shard_mode == "spatial"
    tiles = cfg.cd_shard_mode == "tiles"
    block = min(cfg.cd_block, 256)
    due = (rc.sort_t < 0) | (s.simt - rc.sort_t >= period)

    def fire(args):
        s, rc = args
        if tiles:
            s2, newslot_r, gbits = asasmod.inscan_tile_refresh(
                s, cfg.asas, cfg.cd_tile_shape, block=block,
                budgets=cfg.cd_tile_budgets)
            newslot = newslot_r[rc.newslot]
        elif spatial:
            ndev = cfg.cd_mesh.shape[cfg.cd_mesh_axis]
            s2, newslot_r, gbits = asasmod.inscan_spatial_refresh(
                s, cfg.asas, ndev, block=block,
                halo_blocks=cfg.cd_halo_blocks)
            newslot = newslot_r[rc.newslot]
        else:
            s2 = asasmod.inscan_sparse_refresh(s, cfg.asas, block=block)
            newslot, gbits = rc.newslot, jnp.zeros((), jnp.int32)
        # sort_t advances even on a guarded (skipped) refresh: the edge
        # trips the fallback anyway, and refiring every step would hoist
        # the full sort cost into every iteration.
        return s2, RefreshPack(sort_t=s.simt, count=rc.count + 1,
                               guard=rc.guard | gbits, newslot=newslot)

    return jax.lax.cond(due, fire, lambda a: a, (s, rc))


def _refresh_gate_worlds(s: SimState, rc: RefreshPack, cfg: SimConfig):
    """Multi-world refresh gate: [W] due mask, hoisted ``any-world-due``
    cond around the vmapped sparse refresh + per-world select (the
    step_worlds gate idiom).  Worlds are single-device sparse only
    (``_check_worlds_cfg`` refuses spatial), so no permutation/guard."""
    period = jnp.asarray(float(cfg.asas.sort_every * cfg.asas.dtasas),
                         s.simt.dtype)
    block = min(cfg.cd_block, 256)
    due = (rc.sort_t < 0) | (s.simt - rc.sort_t >= period)   # [W]

    def fire(args):
        s, rc = args
        new = jax.vmap(lambda sw: asasmod.inscan_sparse_refresh(
            sw, cfg.asas, block=block))(s)
        s2 = _select_worlds(due, new, s)
        return s2, RefreshPack(
            sort_t=jnp.where(due, s.simt, rc.sort_t),
            count=rc.count + due.astype(jnp.int32),
            guard=rc.guard, newslot=rc.newslot)

    return jax.lax.cond(jnp.any(due), fire, lambda a: a, (s, rc))


def _scan_steps(state: SimState, cfg: SimConfig, nsteps: int,
                checked: bool, sort_t0=None):
    """The ONE chunk-scan body every runner shares: ``checked`` folds
    the integrity guard into the carry (first-bad-step index, -1 clean).
    Single source of truth so the guard semantics measured by
    guard_overhead.py are exactly the ones the sim runs.

    Returns ``(state, bad, stats, refresh, fp)``: ``bad`` is None unless
    checked, ``stats`` is None unless ``cfg.scanstats`` rides the
    in-scan telemetry accumulators (obs/scanstats.py) through the
    carry, ``refresh`` is None unless ``inscan_refresh_active(cfg)``
    folds the sort refresh into the scan (RefreshPack; ``sort_t0`` is
    the host's last-refresh time seeding its due gate), ``fp`` is None
    unless ``cfg.fingerprint`` folds the SDC state fingerprint
    (obs/fingerprint.py) through the carry.  All flags are jit-static,
    so the all-off branch below IS the original scan, character for
    character — identical traced HLO (``cfg.fingerprint`` dispatches to
    ``_scan_steps_fp`` FIRST, so the branches below never change)."""
    if cfg.fingerprint:
        return _scan_steps_fp(state, cfg, nsteps, checked, sort_t0)
    if inscan_refresh_active(cfg):
        return _scan_steps_inscan(state, cfg, nsteps, checked, sort_t0)
    if cfg.scanstats:
        from ..obs import scanstats as ssmod
        stats0 = ssmod.init(state, cfg)
        if checked:
            def body(carry, i):
                s, bad, st = carry
                s = step(s, cfg)
                bad = jnp.where(bad >= 0, bad,
                                jnp.where(state_finite(s), -1, i))
                return (s, bad, ssmod.fold(st, s, cfg)), None

            (state, bad, stats), _ = jax.lax.scan(
                body, (state, jnp.full((), -1, jnp.int32), stats0),
                jnp.arange(nsteps, dtype=jnp.int32))
            return state, bad, stats, None, None

        def body(carry, _):
            s, st = carry
            s = step(s, cfg)
            return (s, ssmod.fold(st, s, cfg)), None

        (state, stats), _ = jax.lax.scan(body, (state, stats0), None,
                                         length=nsteps)
        return state, None, stats, None, None

    if checked:
        def body(carry, i):
            s, bad = carry
            s = step(s, cfg)
            bad = jnp.where(bad >= 0, bad,
                            jnp.where(state_finite(s), -1, i))
            return (s, bad), None

        (state, bad), _ = jax.lax.scan(
            body, (state, jnp.full((), -1, jnp.int32)),
            jnp.arange(nsteps, dtype=jnp.int32))
        return state, bad, None, None, None

    def body(s, _):
        return step(s, cfg), None

    state, _ = jax.lax.scan(body, state, None, length=nsteps)
    return state, None, None, None, None


def _scan_steps_fp(state: SimState, cfg: SimConfig, nsteps: int,
                   checked: bool, sort_t0):
    """``_scan_steps`` with the SDC fingerprint fold threaded through
    the carry (``cfg.fingerprint``).  One generic dict-carry body
    covers every checked/scanstats/inscan combination instead of
    doubling the hand-split branches above — the fingerprint-ON program
    has no bit-identity contract to preserve (OFF does, and never
    reaches this function), so the carry pytree is assembled per
    jit-static flag and the scan always runs over a step-index arange.
    """
    from ..obs import fingerprint as fpmod
    inscan = inscan_refresh_active(cfg)
    if cfg.scanstats:
        from ..obs import scanstats as ssmod
    carry = dict(s=state, fp=fpmod.init(state, cfg))
    if checked:
        carry["bad"] = jnp.full((), -1, jnp.int32)
    if cfg.scanstats:
        carry["st"] = ssmod.init(state, cfg)
    if inscan:
        carry["rc"] = _refresh_init(state, cfg, sort_t0)

    def body(c, i):
        s, rc = c["s"], c.get("rc")
        if rc is not None:
            s, rc = _refresh_gate(s, rc, cfg)
        s = step(s, cfg)
        out = dict(s=s, fp=fpmod.fold(c["fp"], s, cfg))
        if checked:
            out["bad"] = jnp.where(c["bad"] >= 0, c["bad"],
                                   jnp.where(state_finite(s), -1, i))
        if cfg.scanstats:
            out["st"] = ssmod.fold(c["st"], s, cfg)
        if rc is not None:
            out["rc"] = rc
        return out, None

    carry, _ = jax.lax.scan(body, carry,
                            jnp.arange(nsteps, dtype=jnp.int32))
    return (carry["s"], carry.get("bad"), carry.get("st"),
            carry.get("rc"), carry["fp"])


def _scan_steps_inscan(state: SimState, cfg: SimConfig, nsteps: int,
                       checked: bool, sort_t0):
    """``_scan_steps`` with the refresh gate threaded through the carry
    (``inscan_refresh_active``).  Kept as a separate function so the
    refresh-off branches above stay the original scan verbatim."""
    rc0 = _refresh_init(state, cfg, sort_t0)
    if cfg.scanstats:
        from ..obs import scanstats as ssmod
        stats0 = ssmod.init(state, cfg)
        if checked:
            def body(carry, i):
                s, bad, st, rc = carry
                s, rc = _refresh_gate(s, rc, cfg)
                s = step(s, cfg)
                bad = jnp.where(bad >= 0, bad,
                                jnp.where(state_finite(s), -1, i))
                return (s, bad, ssmod.fold(st, s, cfg), rc), None

            (state, bad, stats, rc), _ = jax.lax.scan(
                body, (state, jnp.full((), -1, jnp.int32), stats0, rc0),
                jnp.arange(nsteps, dtype=jnp.int32))
            return state, bad, stats, rc, None

        def body(carry, _):
            s, st, rc = carry
            s, rc = _refresh_gate(s, rc, cfg)
            s = step(s, cfg)
            return (s, ssmod.fold(st, s, cfg), rc), None

        (state, stats, rc), _ = jax.lax.scan(
            body, (state, stats0, rc0), None, length=nsteps)
        return state, None, stats, rc, None

    if checked:
        def body(carry, i):
            s, bad, rc = carry
            s, rc = _refresh_gate(s, rc, cfg)
            s = step(s, cfg)
            bad = jnp.where(bad >= 0, bad,
                            jnp.where(state_finite(s), -1, i))
            return (s, bad, rc), None

        (state, bad, rc), _ = jax.lax.scan(
            body, (state, jnp.full((), -1, jnp.int32), rc0),
            jnp.arange(nsteps, dtype=jnp.int32))
        return state, bad, None, rc, None

    def body(carry, _):
        s, rc = carry
        s, rc = _refresh_gate(s, rc, cfg)
        return (step(s, cfg), rc), None

    (state, rc), _ = jax.lax.scan(body, (state, rc0), None,
                                  length=nsteps)
    return state, None, None, rc, None


@partial(jax.jit, static_argnames=("cfg", "nsteps"), donate_argnums=0)
def run_steps(state: SimState, cfg: SimConfig, nsteps: int) -> SimState:
    """Advance nsteps with one compiled scan; state buffers are donated.

    This is the reference's lockstep ``STEP``/fast-forward chunk
    (simulation.py:216-223) as a single device program: host syncs once per
    chunk, matching SURVEY.md §2.10's "lax.scan over k steps inside one jit".
    """
    state, _, _, _, _ = _scan_steps(state, cfg, nsteps, checked=False)
    return state


#: Per-aircraft fields the in-scan integrity guard watches.  A non-finite
#: value anywhere in the pipeline reaches one of these within a step or
#: two (vs -> alt, trk/gsnorth/gseast -> lat/lon, thrust/drag -> tas), so
#: guarding the kinematic outputs bounds detection latency to ~one step
#: while keeping the check to a single fused reduce.
GUARD_FIELDS = ("lat", "lon", "alt", "tas", "gs", "vs")


def state_finite(state: SimState) -> jnp.ndarray:
    """Scalar bool: every guarded field is finite on the live rows.

    Padding rows are excluded: they hold whatever the freeze preserved
    and are masked everywhere downstream, so only live-row corruption
    counts as a trip.
    """
    ac = state.ac
    bad = jnp.zeros_like(ac.active)
    for f in GUARD_FIELDS:
        bad |= ~jnp.isfinite(getattr(ac, f))
    return ~jnp.any(bad & ac.active)


@partial(jax.jit, static_argnames=("cfg", "nsteps"), donate_argnums=0)
def run_steps_checked(state: SimState, cfg: SimConfig, nsteps: int):
    """``run_steps`` with the state-integrity guard folded into the scan
    carry: returns ``(state, bad_step)`` where ``bad_step`` is the index
    of the FIRST step (0-based within the chunk) whose post-step state
    had a non-finite guarded value on a live row, or -1 for a clean
    chunk.  The per-step cost is one fused isfinite all-reduce over the
    guarded [N] columns — measured < 2% of the full pipeline at N=100k
    (BENCH_GUARD.json) — and the step index gives the host the bisection
    for free: the fault is pinned to one simdt without re-running the
    chunk.
    """
    state, bad, _, _, _ = _scan_steps(state, cfg, nsteps, checked=True)
    return state, bad


class EdgeTelemetry(NamedTuple):
    """Packed chunk-edge telemetry: everything the host's chunk-edge
    subsystems (guard response, metrics, trails, ACDATA stream) read
    from the device, as SEPARATE output buffers of the chunk program.

    Two properties make the pipelined chunk loop possible:

    * These are *outputs*, never aliases of the (donated) state buffers
      — so the host can dispatch the NEXT chunk (donating the state)
      and still read this edge's values while it runs.
    * The whole pack transfers as ONE device->host copy
      (``jax.device_get`` on the tuple), replacing the dozens of
      per-field ``np.asarray`` pulls metrics/ScreenIO used to issue per
      chunk edge; ``bad`` alone is a one-scalar poll (the deferred
      guard word).

    Observability contract (docs/OBSERVABILITY.md): the flight
    recorder's chunk-sequence correlation tag is HOST-side state on
    ``simulation.pipeline.ChunkEdge``, stamped at dispatch — it must
    NOT become a field here.  Adding a device op for telemetry would
    break the recorder-off guarantee (zero added device ops,
    bit-identical stepped state, pinned by tests/test_obs.py).
    """
    simt: jnp.ndarray       # [s] sim time at the chunk edge
    bad: jnp.ndarray        # int32 first bad step in chunk, -1 = clean
    nconf_cur: jnp.ndarray  # scalar int32 directional conflict count
    nlos_cur: jnp.ndarray   # scalar int32 directional LoS count
    # Per-aircraft kinematic fields (metrics + ACDATA consumers)
    active: jnp.ndarray
    lat: jnp.ndarray
    lon: jnp.ndarray
    alt: jnp.ndarray
    hdg: jnp.ndarray
    trk: jnp.ndarray
    tas: jnp.ndarray
    gs: jnp.ndarray
    cas: jnp.ndarray
    vs: jnp.ndarray
    # ASAS display fields (ACDATA)
    inconf: jnp.ndarray
    tcpamax: jnp.ndarray
    asasn: jnp.ndarray
    asase: jnp.ndarray


def pack_telemetry(state: SimState, bad=None) -> EdgeTelemetry:
    """Build the edge pack from a post-chunk state (inside jit)."""
    ac, asas = state.ac, state.asas
    if bad is None:
        bad = jnp.full((), -1, jnp.int32)
    return EdgeTelemetry(
        simt=state.simt, bad=bad,
        nconf_cur=asas.nconf_cur, nlos_cur=asas.nlos_cur,
        active=ac.active, lat=ac.lat, lon=ac.lon, alt=ac.alt,
        hdg=ac.hdg, trk=ac.trk, tas=ac.tas, gs=ac.gs, cas=ac.cas,
        vs=ac.vs, inconf=asas.inconf, tcpamax=asas.tcpamax,
        asasn=asas.asasn, asase=asas.asase)


def _edge_scan(state: SimState, cfg: SimConfig, nsteps: int,
               checked: bool, sort_t0=None):
    """``(state, telemetry)`` — extended with ``stats`` when
    ``cfg.scanstats`` adds the in-scan accumulator pack and/or the
    ``RefreshPack`` when ``inscan_refresh_active(cfg)`` and/or the
    ``FingerprintPack`` when ``cfg.fingerprint`` (always in that
    order).  The arity pivots on jit-STATIC flags, so each config key
    compiles one fixed output pytree; the extra packs join the
    telemetry as non-donated outputs and ride the same lazy chunk-edge
    pull."""
    state, bad, stats, refresh, fp = _scan_steps(state, cfg, nsteps,
                                                 checked, sort_t0)
    telem = pack_telemetry(state, bad)
    out = (state, telem)
    if stats is not None:
        out = out + (stats,)
    if refresh is not None:
        out = out + (refresh,)
    if fp is not None:
        out = out + (fp,)
    return out


@partial(jax.jit, static_argnames=("cfg", "nsteps", "checked"),
         donate_argnums=0)
def run_steps_edge(state: SimState, cfg: SimConfig, nsteps: int,
                   checked: bool = False, sort_t0=None):
    """``run_steps`` (or the guarded scan, ``checked=True``) returning
    ``(state, EdgeTelemetry)``.  State buffers are donated like
    ``run_steps``; the telemetry pack is materialized as separate
    buffers so it survives the next chunk's donation — the enabling
    contract of the pipelined chunk loop (simulation/sim.py).
    ``sort_t0`` (traced scalar, or the previous chunk's RefreshPack
    ``sort_t`` device buffer) seeds the in-scan refresh gate when
    ``cfg.inscan_refresh`` is on; None otherwise (empty pytree — the
    OFF program is unchanged)."""
    return _edge_scan(state, cfg, nsteps, checked, sort_t0)


@partial(jax.jit, static_argnames=("cfg", "nsteps", "checked"))
def run_steps_edge_keep(state: SimState, cfg: SimConfig, nsteps: int,
                        checked: bool = False, sort_t0=None):
    """``run_steps_edge`` WITHOUT input donation: the caller keeps the
    pre-chunk state buffers valid.  The pipelined loop uses this for
    the chunk after a snapshot-ring capture edge, so the full pre-chunk
    pytree can be copied to the host *while the next chunk runs*
    instead of blocking the dispatch (the off-critical-path capture)."""
    return _edge_scan(state, cfg, nsteps, checked, sort_t0)


step_jit = jax.jit(step, static_argnames=("cfg",))


# --------------------------------------------------------------- multi-world
# Batched multi-world stepping: the same scan with a leading WORLD axis
# on the whole SimState pytree, so ONE device program advances W
# independent scenarios per dispatch (docs/PERF_ANALYSIS.md
# §multi-world).  Per-world scalars (simt, rng, nconf/nlos, the guard
# word) ride the pytree and become [W]-vectors for free; per-world
# clocks may differ, so worlds at different sim times batch together.
# One compile per (nmax-bucket, chunk-length, cfg) key serves every
# fleet of compatible scenarios — the serving layer packs compatible
# BATCH pieces into exactly these batches (network/server.py).


def _check_worlds_cfg(cfg: SimConfig):
    """World batching composes with single-device configs only: the
    mesh decompositions put per-DEVICE structure on the aircraft axis
    (spatial stripes are a property of one world's sorted layout), so
    they compose with the world axis later, not now."""
    if cfg.cd_mesh is not None \
            or cfg.cd_shard_mode in ("spatial", "tiles"):
        raise ValueError(
            "world-batched stepping runs single-device per world: "
            "cd_mesh must be None and cd_shard_mode != "
            "'spatial'/'tiles' (pack refuses sharded pieces — see "
            "WORLDS docs)")


def stack_worlds(states) -> SimState:
    """Stack a list of same-shape SimStates into one [W, ...] pytree."""
    states = list(states)
    if not states:
        raise ValueError("stack_worlds: need at least one world")
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *states)


def world_slice(wtree, w: int):
    """World ``w``'s slice of any stacked pytree (state or telemetry)."""
    return jax.tree_util.tree_map(lambda x: x[w], wtree)


def unstack_worlds(wstate: SimState):
    """Split a stacked state back into per-world SimStates."""
    nw = int(wstate.simt.shape[0])
    return [world_slice(wstate, w) for w in range(nw)]


def _select_worlds(mask, new_tree, old_tree):
    """Per-world select: ``mask`` is [W] bool, tree leaves are [W, ...];
    worlds where mask is False keep their old leaves bit-exactly."""
    def sel(new, old):
        m = mask.reshape(mask.shape + (1,) * (new.ndim - 1))
        return jnp.where(m, new, old)
    return jax.tree_util.tree_map(sel, new_tree, old_tree)


def step_worlds(state: SimState, cfg: SimConfig) -> SimState:
    """One simdt for every world of a stacked [W, ...] state.

    Semantically ``jax.vmap(step)`` — and bit-identical to it (the W=1
    parity test pins this against the UNBATCHED step) — but with the
    time-staggered gates hoisted out of the vmap: under plain vmap a
    ``lax.cond`` lowers to a select that runs BOTH branches every step,
    so the 1 Hz ASAS interval and the ~1 s FMS update would burn their
    full cost every 0.05 s step in every world (~20x the arithmetic —
    measured 25x slower than unbatched, the opposite of batching).
    Here each gate is a scalar ``any world due`` cond around the
    vmapped branch plus a per-world select, so a step where NO world
    hits the gate (19 of 20 at the default cadences) skips the branch
    exactly like the single-world scan does; packed scenarios share
    their cadence by construction (same SimConfig), so the union
    schedule stays the single-world schedule even for worlds at
    different sim times.
    """
    simt = state.simt                             # [W]

    # ---------- Atmosphere ----------
    state = state.replace(
        ac=jax.vmap(kinematics.update_atmosphere)(state.ac))

    # ---------- ADS-B broadcast model ----------
    if cfg.noise.turb_active or cfg.noise.adsb_transnoise:
        rng, k_adsb, k_turb = jax.vmap(
            lambda k: tuple(jax.random.split(k, 3)))(state.rng)
    else:
        rng = k_adsb = k_turb = state.rng
    state = state.replace(
        rng=rng,
        adsb=jax.vmap(lambda a, ac, k, t: noise.adsb_update(
            a, ac, k, t, cfg.noise,
            smooth=cfg.smooth))(state.adsb, state.ac, k_adsb, simt))

    # ---------- FMS / autopilot, gated at fms_dt ----------
    fms_due = (state.fms_t0 + cfg.fms_dt < simt) | (simt < state.fms_t0) \
        | (simt < cfg.fms_dt)                     # [W]

    def run_fms_worlds(s):
        new = jax.vmap(autopilot.update_fms)(s)
        new = new.replace(fms_t0=simt)
        return _select_worlds(fms_due, new, s)

    state = jax.lax.cond(jnp.any(fms_due), run_fms_worlds,
                         lambda s: s, state)
    state = jax.vmap(autopilot.update_continuous)(state)

    # ---------- ASAS CD&R, gated at dtasas ----------
    if cfg.asas.swasas:
        if cfg.cd_backend not in ("dense", "tiled", "pallas", "sparse"):
            raise ValueError(
                f"Unknown SimConfig.cd_backend {cfg.cd_backend!r}; "
                "expected 'dense', 'tiled', 'pallas' or 'sparse'.")
        if cfg.cd_backend == "dense" and state.asas.resopairs.size == 0:
            raise ValueError(
                "State was allocated with pair_matrix=False (no [N,N] "
                "resopairs) but SimConfig.cd_backend is 'dense'. Use "
                "SimConfig(cd_backend='tiled') or allocate "
                "Traffic(pair_matrix=True).")
        asas_due = simt >= state.asas_tnext       # [W]

        def run_asas_worlds(s):
            def one(sw):
                if cfg.cd_backend in ("tiled", "pallas", "sparse"):
                    impl = asasmod.impl_for_backend(cfg.cd_backend)
                    s2, _cd = asasmod.update_tiled(
                        sw, cfg.asas, block=cfg.cd_block, impl=impl,
                        mesh=cfg.cd_mesh, mesh_axis=cfg.cd_mesh_axis,
                        shard_mode=cfg.cd_shard_mode,
                        halo_blocks=cfg.cd_halo_blocks)
                else:
                    s2, _cd = asasmod.update(sw, cfg.asas,
                                             smooth=cfg.smooth)
                return s2.replace(
                    asas_tnext=sw.asas_tnext
                    + jnp.asarray(cfg.asas.dtasas, sw.asas_tnext.dtype))
            return _select_worlds(asas_due, jax.vmap(one)(s), s)

        state = jax.lax.cond(jnp.any(asas_due), run_asas_worlds,
                             lambda s: s, state)

    # ---------- Pilot arbitration / perf / kinematics / noise ----------
    def tail(sw, kt):
        if cfg.use_wind:
            windn, winde = windmod.getdata(sw.wind, sw.ac.lat,
                                           sw.ac.lon, sw.ac.alt)
        else:
            windn = winde = None
        sw = pilot.ap_or_asas(sw, windn, winde)
        new_perf, bank = perfmod.update(sw.perf, sw.ac.tas, sw.ac.vs,
                                        sw.ac.alt)
        sw = sw.replace(perf=new_perf, ac=sw.ac.replace(bank=bank))
        sw = pilot.apply_limits(sw, smooth=cfg.smooth)
        accel = perfmod.acceleration(sw.perf.phase)
        ac = kinematics.update_airspeed(sw.ac, sw.pilot, accel,
                                        jnp.asarray(cfg.simdt,
                                                    sw.simt.dtype),
                                        smooth=cfg.smooth)
        ac = kinematics.update_groundspeed(ac, windn, winde)
        ac = kinematics.update_position(ac, sw.pilot,
                                        jnp.asarray(cfg.simdt,
                                                    sw.simt.dtype))
        ac = noise.turbulence_woosh(ac, kt, jnp.asarray(
            cfg.simdt, sw.simt.dtype), cfg.noise, smooth=cfg.smooth)
        live = ac.active
        frz = lambda new, old: jnp.where(live, new, old)
        ac = ac.replace(
            lat=frz(ac.lat, sw.ac.lat), lon=frz(ac.lon, sw.ac.lon),
            alt=frz(ac.alt, sw.ac.alt), hdg=frz(ac.hdg, sw.ac.hdg),
            trk=frz(ac.trk, sw.ac.trk), tas=frz(ac.tas, sw.ac.tas),
            gs=frz(ac.gs, sw.ac.gs), vs=frz(ac.vs, sw.ac.vs))
        return sw.replace(ac=ac, simt=sw.simt + jnp.asarray(
            cfg.simdt, sw.simt.dtype))

    return jax.vmap(tail)(state, k_turb)


def _scan_steps_worlds(state: SimState, cfg: SimConfig, nsteps: int,
                       checked: bool, sort_t0=None):
    """The chunk scan with a leading world axis: a scan of the batched
    step (ONE scan, the batch dim pushed into the body), with the
    integrity guard widened to a [W] vector of first-bad-step indices
    (-1 clean) so a trip pins the (world, step) pair.

    Same ``(state, bad, stats, refresh, fp)`` contract as
    ``_scan_steps``; with ``cfg.scanstats`` the accumulators get a
    leading [W] axis (vmapped init/fold — worlds are single-device, so
    every fold stays the P=1 flavour) and demux per world via
    ``world_slice`` like telemetry.  With ``inscan_refresh_active(cfg)``
    the RefreshPack scalars widen to [W] the same way (``sort_t0`` is a
    [W] vector of per-world last-refresh times); with
    ``cfg.fingerprint`` the FingerprintPack does too (dispatched FIRST
    to ``_scan_steps_worlds_fp`` so the branches below never change)."""
    vstep = lambda s: step_worlds(s, cfg)
    if cfg.fingerprint:
        return _scan_steps_worlds_fp(state, cfg, nsteps, checked,
                                     sort_t0)
    if inscan_refresh_active(cfg):
        return _scan_steps_worlds_inscan(state, cfg, nsteps, checked,
                                         sort_t0)
    if cfg.scanstats:
        from ..obs import scanstats as ssmod
        stats0 = jax.vmap(lambda s: ssmod.init(s, cfg))(state)
        vfold = jax.vmap(lambda st, s: ssmod.fold(st, s, cfg))
        if checked:
            nworlds = state.simt.shape[0]
            vfinite = jax.vmap(state_finite)

            def body(carry, i):
                s, bad, st = carry
                s = vstep(s)
                bad = jnp.where(bad >= 0, bad,
                                jnp.where(vfinite(s), -1, i))
                return (s, bad, vfold(st, s)), None

            (state, bad, stats), _ = jax.lax.scan(
                body, (state, jnp.full((nworlds,), -1, jnp.int32),
                       stats0),
                jnp.arange(nsteps, dtype=jnp.int32))
            return state, bad, stats, None, None

        def body(carry, _):
            s, st = carry
            s = vstep(s)
            return (s, vfold(st, s)), None

        (state, stats), _ = jax.lax.scan(body, (state, stats0), None,
                                         length=nsteps)
        return state, None, stats, None, None

    if checked:
        nworlds = state.simt.shape[0]
        vfinite = jax.vmap(state_finite)

        def body(carry, i):
            s, bad = carry
            s = vstep(s)
            bad = jnp.where(bad >= 0, bad,
                            jnp.where(vfinite(s), -1, i))
            return (s, bad), None

        (state, bad), _ = jax.lax.scan(
            body, (state, jnp.full((nworlds,), -1, jnp.int32)),
            jnp.arange(nsteps, dtype=jnp.int32))
        return state, bad, None, None, None

    def body(s, _):
        return vstep(s), None

    state, _ = jax.lax.scan(body, state, None, length=nsteps)
    return state, None, None, None, None


def _scan_steps_worlds_fp(state: SimState, cfg: SimConfig, nsteps: int,
                          checked: bool, sort_t0):
    """``_scan_steps_fp`` with a leading world axis: the same generic
    dict carry, with vmapped fingerprint/stats init+fold (worlds are
    single-device, so every fold stays the P=1 flavour — the pack
    demuxes per world via ``world_slice`` like telemetry)."""
    from ..obs import fingerprint as fpmod
    inscan = inscan_refresh_active(cfg)
    if cfg.scanstats:
        from ..obs import scanstats as ssmod
        vsfold = jax.vmap(lambda st, s: ssmod.fold(st, s, cfg))
    vstep = lambda s: step_worlds(s, cfg)
    vfinite = jax.vmap(state_finite)
    vffold = jax.vmap(lambda f, s: fpmod.fold(f, s, cfg))
    nworlds = state.simt.shape[0]
    carry = dict(s=state,
                 fp=jax.vmap(lambda s: fpmod.init(s, cfg))(state))
    if checked:
        carry["bad"] = jnp.full((nworlds,), -1, jnp.int32)
    if cfg.scanstats:
        carry["st"] = jax.vmap(lambda s: ssmod.init(s, cfg))(state)
    if inscan:
        carry["rc"] = _refresh_init(state, cfg, sort_t0, worlds=True)

    def body(c, i):
        s, rc = c["s"], c.get("rc")
        if rc is not None:
            s, rc = _refresh_gate_worlds(s, rc, cfg)
        s = vstep(s)
        out = dict(s=s, fp=vffold(c["fp"], s))
        if checked:
            out["bad"] = jnp.where(c["bad"] >= 0, c["bad"],
                                   jnp.where(vfinite(s), -1, i))
        if cfg.scanstats:
            out["st"] = vsfold(c["st"], s)
        if rc is not None:
            out["rc"] = rc
        return out, None

    carry, _ = jax.lax.scan(body, carry,
                            jnp.arange(nsteps, dtype=jnp.int32))
    return (carry["s"], carry.get("bad"), carry.get("st"),
            carry.get("rc"), carry["fp"])


def _scan_steps_worlds_inscan(state: SimState, cfg: SimConfig,
                              nsteps: int, checked: bool, sort_t0):
    """``_scan_steps_worlds`` with the per-world refresh gate in the
    carry; separate function so the refresh-off branches above stay the
    original scan verbatim (the ``_scan_steps_inscan`` split)."""
    vstep = lambda s: step_worlds(s, cfg)
    rc0 = _refresh_init(state, cfg, sort_t0, worlds=True)
    if cfg.scanstats:
        from ..obs import scanstats as ssmod
        stats0 = jax.vmap(lambda s: ssmod.init(s, cfg))(state)
        vfold = jax.vmap(lambda st, s: ssmod.fold(st, s, cfg))
        if checked:
            nworlds = state.simt.shape[0]
            vfinite = jax.vmap(state_finite)

            def body(carry, i):
                s, bad, st, rc = carry
                s, rc = _refresh_gate_worlds(s, rc, cfg)
                s = vstep(s)
                bad = jnp.where(bad >= 0, bad,
                                jnp.where(vfinite(s), -1, i))
                return (s, bad, vfold(st, s), rc), None

            (state, bad, stats, rc), _ = jax.lax.scan(
                body, (state, jnp.full((nworlds,), -1, jnp.int32),
                       stats0, rc0),
                jnp.arange(nsteps, dtype=jnp.int32))
            return state, bad, stats, rc, None

        def body(carry, _):
            s, st, rc = carry
            s, rc = _refresh_gate_worlds(s, rc, cfg)
            s = vstep(s)
            return (s, vfold(st, s), rc), None

        (state, stats, rc), _ = jax.lax.scan(
            body, (state, stats0, rc0), None, length=nsteps)
        return state, None, stats, rc, None

    if checked:
        nworlds = state.simt.shape[0]
        vfinite = jax.vmap(state_finite)

        def body(carry, i):
            s, bad, rc = carry
            s, rc = _refresh_gate_worlds(s, rc, cfg)
            s = vstep(s)
            bad = jnp.where(bad >= 0, bad,
                            jnp.where(vfinite(s), -1, i))
            return (s, bad, rc), None

        (state, bad, rc), _ = jax.lax.scan(
            body, (state, jnp.full((nworlds,), -1, jnp.int32), rc0),
            jnp.arange(nsteps, dtype=jnp.int32))
        return state, bad, None, rc, None

    def body(carry, _):
        s, rc = carry
        s, rc = _refresh_gate_worlds(s, rc, cfg)
        return (vstep(s), rc), None

    (state, rc), _ = jax.lax.scan(body, (state, rc0), None,
                                  length=nsteps)
    return state, None, None, rc, None


@partial(jax.jit, static_argnames=("cfg", "nsteps"), donate_argnums=0)
def run_steps_worlds(state: SimState, cfg: SimConfig,
                     nsteps: int) -> SimState:
    """``run_steps`` over a stacked [W, ...] state: W scenarios advance
    nsteps in one compiled scan.  W=1 is bit-identical to the unbatched
    path (tests/test_worlds.py pins this)."""
    _check_worlds_cfg(cfg)
    state, _, _, _, _ = _scan_steps_worlds(state, cfg, nsteps,
                                           checked=False)
    return state


@partial(jax.jit, static_argnames=("cfg", "nsteps"), donate_argnums=0)
def run_steps_worlds_checked(state: SimState, cfg: SimConfig,
                             nsteps: int):
    """Guarded multi-world scan: returns ``(state, bad)`` where ``bad``
    is [W] int32 — per world, the FIRST step index whose post-step
    state had a non-finite guarded value on a live row, or -1 for a
    clean world.  One fused isfinite reduce per world per step; the
    host response (rollback/quarantine) stays per-world because the
    faulty (world, step) pair is pinned without re-running anything."""
    _check_worlds_cfg(cfg)
    state, bad, _, _, _ = _scan_steps_worlds(state, cfg, nsteps,
                                             checked=True)
    return state, bad


def _edge_scan_worlds(state: SimState, cfg: SimConfig, nsteps: int,
                      checked: bool, sort_t0=None):
    state, bad, stats, refresh, fp = _scan_steps_worlds(
        state, cfg, nsteps, checked, sort_t0)
    if bad is None:
        bad = jnp.full((state.simt.shape[0],), -1, jnp.int32)
    telem = jax.vmap(pack_telemetry)(state, bad)
    out = (state, telem)
    if stats is not None:
        out = out + (stats,)
    if refresh is not None:
        out = out + (refresh,)
    if fp is not None:
        out = out + (fp,)
    return out


@partial(jax.jit, static_argnames=("cfg", "nsteps", "checked"),
         donate_argnums=0)
def run_steps_worlds_edge(state: SimState, cfg: SimConfig, nsteps: int,
                          checked: bool = False, sort_t0=None):
    """Multi-world ``run_steps_edge``: ``(state, EdgeTelemetry)`` with a
    leading world axis on every telemetry field.  ``world_slice(telem,
    w)`` is a plain per-world EdgeTelemetry — the serving layer demuxes
    the pack back to the individual BATCH pieces with it.  ``sort_t0``
    is the [W] vector of per-world last-refresh sim times when
    ``cfg.inscan_refresh`` rides (the RefreshPack joins the outputs and
    demuxes via ``world_slice`` like everything else)."""
    _check_worlds_cfg(cfg)
    return _edge_scan_worlds(state, cfg, nsteps, checked, sort_t0)


@partial(jax.jit, static_argnames=("cfg", "nsteps", "checked"))
def run_steps_worlds_edge_keep(state: SimState, cfg: SimConfig,
                               nsteps: int, checked: bool = False,
                               sort_t0=None):
    """``run_steps_worlds_edge`` without input donation (snapshot
    capture overlapping the dispatched chunk, as run_steps_edge_keep)."""
    _check_worlds_cfg(cfg)
    return _edge_scan_worlds(state, cfg, nsteps, checked, sort_t0)
