"""Noise models: turbulence and the ADS-B transmission model (PRNG-keyed).

Parity with reference ``traffic/turbulence.py`` (gaussian positional jitter
in flight/wing/vertical axes scaled by sqrt(dt), turbulence.py:24-46) and
``traffic/adsbmodel.py`` (last-broadcast state with optional gaussian
position/altitude error and truncated update times, adsbmodel.py:44-60).

TPU-first: ``np.random`` becomes explicit `jax.random` keys threaded through
the state — same-seed runs are bitwise reproducible, which is this
framework's substitute for the reference's (absent) race detection story
(SURVEY.md §5.2).
"""
from typing import NamedTuple

import jax
import jax.numpy as jnp
from flax import struct

from ..ops import aero


class NoiseConfig(NamedTuple):
    """Noise switches/levels (reference SetNoise + SetStandards)."""
    turb_active: bool = False
    turb_sd_hf: float = 1e-6    # [m/s] flight-direction sd (ref default 0)
    turb_sd_hw: float = 0.1     # [m/s] wing-direction sd
    turb_sd_vert: float = 0.1   # [m/s] vertical sd
    adsb_transnoise: bool = False
    adsb_truncated: bool = False
    adsb_err_latlon: float = 1e-4        # [deg]
    adsb_err_alt: float = 100.0 * aero.ft  # [m]
    adsb_trunctime: float = 0.0          # [s]


@struct.dataclass
class AdsbArrays:
    """Last-broadcast surveillance state (reference adsbmodel.py:14-23)."""
    lastupdate: jnp.ndarray
    lat: jnp.ndarray
    lon: jnp.ndarray
    alt: jnp.ndarray
    trk: jnp.ndarray
    tas: jnp.ndarray
    gs: jnp.ndarray
    vs: jnp.ndarray


def make_adsb(nmax: int, dtype=jnp.float32) -> AdsbArrays:
    z = lambda: jnp.zeros((nmax,), dtype)
    return AdsbArrays(lastupdate=z(), lat=z(), lon=z(), alt=z(),
                      trk=z(), tas=z(), gs=z(), vs=z())


def turbulence_woosh(ac, key, simdt, cfg: NoiseConfig, smooth=None):
    """Positional turbulence jitter (turbulence.py:24-46).

    ``smooth`` (differentiable mode, diff/smooth.py): the gaussian
    draws are stop-gradiented — they are parameter-independent by
    construction (the PRNG stream never depends on the optimized
    offsets), and pinning them keeps the backward pass from
    differentiating through ``jax.random`` internals while the additive
    jitter still perturbs the forward rollout."""
    if not cfg.turb_active:
        return ac
    n = ac.lat.shape[0]
    timescale = jnp.sqrt(simdt)
    k1, k2, k3 = jax.random.split(key, 3)
    turbhf = jax.random.normal(k1, (n,), ac.lat.dtype) \
        * (cfg.turb_sd_hf * timescale)
    turbhw = jax.random.normal(k2, (n,), ac.lat.dtype) \
        * (cfg.turb_sd_hw * timescale)
    turbalt = jax.random.normal(k3, (n,), ac.lat.dtype) \
        * (cfg.turb_sd_vert * timescale)
    if smooth is not None and smooth.stop_grad_noise:
        turbhf, turbhw, turbalt = (
            jax.lax.stop_gradient(turbhf), jax.lax.stop_gradient(turbhw),
            jax.lax.stop_gradient(turbalt))

    trkrad = jnp.radians(ac.trk)
    turblat = jnp.cos(trkrad) * turbhf - jnp.sin(trkrad) * turbhw
    turblon = jnp.sin(trkrad) * turbhf + jnp.cos(trkrad) * turbhw

    live = ac.active
    return ac.replace(
        alt=jnp.where(live, ac.alt + turbalt, ac.alt),
        lat=jnp.where(live, ac.lat + jnp.degrees(turblat / aero.Rearth), ac.lat),
        lon=jnp.where(live,
                      ac.lon + jnp.degrees(turblon / aero.Rearth / ac.coslat),
                      ac.lon))


def adsb_update(adsb: AdsbArrays, ac, key, simt, cfg: NoiseConfig,
                smooth=None):
    """Refresh broadcast state for aircraft whose truncation window elapsed
    (adsbmodel.py:44-59).  ``smooth`` stop-gradients the transmission-
    noise draws like ``turbulence_woosh``."""
    up = adsb.lastupdate + cfg.adsb_trunctime < simt
    if cfg.adsb_transnoise:
        n = ac.lat.shape[0]
        k1, k2, k3 = jax.random.split(key, 3)
        err1 = jax.random.normal(k1, (n,), ac.lat.dtype)
        err2 = jax.random.normal(k2, (n,), ac.lat.dtype)
        err3 = jax.random.normal(k3, (n,), ac.lat.dtype)
        if smooth is not None and smooth.stop_grad_noise:
            err1, err2, err3 = (jax.lax.stop_gradient(err1),
                                jax.lax.stop_gradient(err2),
                                jax.lax.stop_gradient(err3))
        lat = ac.lat + err1 * cfg.adsb_err_latlon
        lon = ac.lon + err2 * cfg.adsb_err_latlon
        alt = ac.alt + err3 * cfg.adsb_err_alt
    else:
        lat, lon, alt = ac.lat, ac.lon, ac.alt
    sel = lambda new, old: jnp.where(up, new, old)
    return adsb.replace(
        lat=sel(lat, adsb.lat), lon=sel(lon, adsb.lon), alt=sel(alt, adsb.alt),
        trk=sel(ac.trk, adsb.trk), tas=sel(ac.tas, adsb.tas),
        gs=sel(ac.gs, adsb.gs), vs=sel(ac.vs, adsb.vs),
        lastupdate=jnp.where(up, adsb.lastupdate + cfg.adsb_trunctime,
                             adsb.lastupdate))
