"""Host-side Traffic facade: create/delete/lookup over the device state.

This is the replacement for the reference's ``Traffic`` singleton
(traffic.py:55-756) *minus* the physics (which lives in jitted functions in
this package).  It owns:

* the device ``SimState`` (padded arrays + active mask),
* host-only bookkeeping the device must never see: callsign and type strings,
  the id->slot map (replacing ``id2idx``'s list.index, traffic.py:485-501).

Creation semantics follow reference ``Traffic.create`` (traffic.py:192-312):
random defaults in an area, CAS-or-Mach initial speed, atmosphere init, AP /
active-waypoint / ASAS / ADS-B / performance child rows.  Deletion is a mask
flip (the reference compacts arrays, traffic.py:365-381; slot identity is
stable here, which also keeps the [N,N] pair matrices valid).

Writes are *batched*: stack commands queue slot writes and ``flush()``
applies them in one ``.at[idx].set`` sweep per field before the next step
chunk, so a 4000-line scenario costs a handful of device calls, not 4000.
"""
import os
from typing import List, Optional

import numpy as np
import jax.numpy as jnp

from ..models import perf_coeffs
from ..ops import aero
from .state import SimState, make_state


class Traffic:
    """Host facade over a padded SimState."""

    def __init__(self, nmax: int = 64, wmax: int = 32, dtype=jnp.float32,
                 openap_path: Optional[str] = None, rng_seed: int = 0,
                 area=(-1.0, 1.0, -1.0, 1.0),
                 pair_matrix: bool = True, k_partners: int = 8):
        self.nmax = nmax
        self.wmax = wmax
        self.dtype = dtype
        self.pair_matrix = pair_matrix
        self.k_partners = k_partners
        self.state: SimState = make_state(nmax, wmax, dtype, rng_seed,
                                          pair_matrix, k_partners)
        from .. import settings
        model = getattr(settings, "performance_model", "openap")
        if openap_path is None and model == "openap":
            # Default to the real OpenAP coefficient data when present
            # (settings.perf_path/OpenAP; reference coeff.py:7,16-19),
            # falling back to the built-in approximate tables.
            cand = os.path.join(settings.perf_path, "OpenAP")
            if os.path.isdir(os.path.join(cand, "fixwing")):
                openap_path = cand
            elif not getattr(Traffic, "_warned_builtin", False):
                Traffic._warned_builtin = True
                print(f"perf: no OpenAP coefficient data at {cand} — "
                      "using the BUILTIN approximate set (unknown types "
                      "map to 'NA'; see docs/DATA.md)")
        self.coeffdb = perf_coeffs.CoeffDB(openap_path, model=model,
                                           perf_path=settings.perf_path)
        self.area = area  # default creation area (lat0, lat1, lon0, lon1)
        self._rng = np.random.default_rng(rng_seed)
        # Host-side per-slot bookkeeping
        self.ids: List[Optional[str]] = [None] * nmax
        self.types: List[Optional[str]] = [None] * nmax
        self._id2slot = {}
        self._pending = []          # queued creation dicts
        self._autoid = 0
        # Observers notified with an old->new slot map when the SPATIAL
        # shard refresh re-buckets caller slots by latitude stripe
        # (parallel/sharding.prepare_spatial; routes/conditions/trails
        # register here).  Slots remain stable between refreshes; any
        # host subsystem caching slot indices across chunk edges in
        # spatial mode must subscribe.  (Defined before Trails below —
        # it subscribes at construction.)
        self.permute_hooks = []
        # Display trails (reference traffic.py:79 bs.traf.trails)
        from .trails import Trails
        self.trails = Trails(self)
        # Observers notified with slot indices on deletion (conditional
        # commands, AREA plugin, ... — reference cond.delac wiring) and on
        # creation flush (slot array; reference TrafficArrays.create cascade)
        self.delete_hooks = []
        self.create_hooks = []

    def apply_slot_permutation(self, newslot):
        """Re-bucket host bookkeeping after a spatial shard refresh
        moved aircraft between caller slots (``newslot[old] = new``).
        The device state was already permuted by the refresh; this
        remaps ids/types and fans out to ``permute_hooks``."""
        newslot = np.asarray(newslot)
        src = np.empty(self.nmax, dtype=np.intp)      # new -> old slot
        src[newslot] = np.arange(self.nmax, dtype=np.intp)
        self.ids = np.asarray(self.ids, dtype=object)[src].tolist()
        self.types = np.asarray(self.types, dtype=object)[src].tolist()
        # remap the live id -> slot map in O(ntraf), not O(nmax)
        self._id2slot = {i: int(newslot[s])
                         for i, s in self._id2slot.items()}
        for hook in self.permute_hooks:
            hook(newslot)

    # ------------------------------------------------------------------ info
    @property
    def ntraf(self) -> int:
        return len(self._id2slot) + len(self._pending)

    def id2idx(self, acid):
        """Slot index of a callsign; -1 if unknown (traffic.py:485-501)."""
        if not isinstance(acid, str):
            return [self.id2idx(a) for a in acid]
        if acid in ('#', '*'):
            # last created
            if self._pending:
                return -2  # pending, unknown slot yet; flush first
            slots = [s for s, i in enumerate(self.ids) if i is not None]
            return slots[-1] if slots else -1
        return self._id2slot.get(acid.upper(), -1)

    # ---------------------------------------------------------------- create
    def create(self, n=1, actype="B744", acalt=None, acspd=None, dest=None,
               aclat=None, aclon=None, achdg=None, acid=None):
        """Queue creation of n aircraft (reference traffic.py:192-252)."""
        if acid is None:
            pre = chr(self._rng.integers(65, 91)) + chr(self._rng.integers(65, 91))
            acid = [f"{pre}{self._autoid + i:>05}" for i in range(n)]
            self._autoid += n
        elif isinstance(acid, str):
            if acid.upper() in self._id2slot:
                return False, acid + " already exists."
            acid = [acid.upper()]
        if isinstance(actype, str):
            actype = n * [actype]

        lat0, lat1, lon0, lon1 = self.area
        if aclat is None:
            aclat = self._rng.random(n) * (lat1 - lat0) + lat0
        if aclon is None:
            aclon = self._rng.random(n) * (lon1 - lon0) + lon0
        aclat = np.atleast_1d(np.asarray(aclat, dtype=np.float64))
        aclon = np.atleast_1d(np.asarray(aclon, dtype=np.float64))
        aclon = np.where(aclon > 180.0, aclon - 360.0, aclon)
        aclon = np.where(aclon < -180.0, aclon + 360.0, aclon)
        if achdg is None:
            achdg = self._rng.integers(1, 360, n).astype(np.float64)
        if acalt is None:
            acalt = self._rng.integers(2000, 39000, n) * aero.ft
        if acspd is None:
            acspd = self._rng.integers(250, 450, n) * aero.kts
        achdg = np.broadcast_to(np.atleast_1d(np.asarray(achdg, np.float64)), (n,))
        acalt = np.broadcast_to(np.atleast_1d(np.asarray(acalt, np.float64)), (n,))
        acspd = np.broadcast_to(np.atleast_1d(np.asarray(acspd, np.float64)), (n,))

        self._pending.append(dict(
            acid=[a.upper() for a in acid], actype=[t.upper() for t in actype],
            lat=aclat, lon=aclon, hdg=achdg, alt=acalt, spd=acspd))
        return True, None

    def _free_slots(self, n):
        free = [i for i, v in enumerate(self.ids) if v is None]
        if len(free) < n:
            raise RuntimeError(
                f"traffic full: need {n} slots, {len(free)} free "
                f"(nmax={self.nmax}); raise nmax")
        return np.asarray(free[:n])

    def flush(self):
        """Apply all queued creations in one batched device write."""
        if not self._pending:
            return
        batch = self._pending
        self._pending = []
        ids = sum((b['acid'] for b in batch), [])
        types = sum((b['actype'] for b in batch), [])
        lat = np.concatenate([b['lat'] for b in batch])
        lon = np.concatenate([b['lon'] for b in batch])
        hdg = np.concatenate([b['hdg'] for b in batch])
        alt = np.concatenate([b['alt'] for b in batch])
        spd = np.concatenate([b['spd'] for b in batch])
        n = len(ids)
        slots = self._free_slots(n)
        for k, (i, t) in enumerate(zip(ids, types)):
            s = int(slots[k])
            self.ids[s] = i
            self.types[s] = t
            self._id2slot[i] = s

        st = self.state
        ac, ap, actwp, asas, adsb = st.ac, st.ap, st.actwp, st.asas, st.adsb

        # Initial speeds: CAS-or-Mach interpretation (traffic.py:268-272)
        import numpy as onp
        tas, cas, mach = (onp.asarray(x) for x in _np_vcasormach(spd, alt))
        hdgrad = onp.radians(hdg)
        gsnorth = tas * onp.cos(hdgrad)
        gseast = tas * onp.sin(hdgrad)
        p, rho, temp = _np_vatmos(alt)

        idx = jnp.asarray(slots)
        put = lambda arr, val: arr.at[idx].set(
            jnp.asarray(val, arr.dtype) if not isinstance(val, (int, float, bool))
            else val)
        ac = ac.replace(
            active=ac.active.at[idx].set(True),
            lat=put(ac.lat, lat), lon=put(ac.lon, lon), alt=put(ac.alt, alt),
            hdg=put(ac.hdg, hdg), trk=put(ac.trk, hdg),
            tas=put(ac.tas, tas), gs=put(ac.gs, tas),
            gsnorth=put(ac.gsnorth, gsnorth), gseast=put(ac.gseast, gseast),
            cas=put(ac.cas, cas), mach=put(ac.mach, mach),
            vs=put(ac.vs, np.zeros(n)),
            p=put(ac.p, p), rho=put(ac.rho, rho), temp=put(ac.temp, temp),
            selspd=put(ac.selspd, cas), selalt=put(ac.selalt, alt),
            selvs=put(ac.selvs, np.zeros(n)),
            swlnav=ac.swlnav.at[idx].set(False),
            swvnav=ac.swvnav.at[idx].set(False),
            abco=ac.abco.at[idx].set(False),
            belco=ac.belco.at[idx].set(True),
            apvsdef=put(ac.apvsdef, np.full(n, 1500.0 * aero.fpm)),
            aphi=put(ac.aphi, np.full(n, np.radians(25.0))),
            ax=put(ac.ax, np.full(n, aero.kts)),
            bank=put(ac.bank, np.full(n, np.radians(25.0))),
            coslat=put(ac.coslat, np.cos(np.radians(lat))),
        )
        # Child rows (reference create() of each TrafficArrays child)
        ap = ap.replace(trk=put(ap.trk, hdg), tas=put(ap.tas, tas),
                        alt=put(ap.alt, alt), vs=put(ap.vs, np.zeros(n)),
                        dist2vs=put(ap.dist2vs, np.full(n, -999.0)))
        actwp = actwp.replace(
            lat=put(actwp.lat, np.full(n, 89.99)),
            lon=put(actwp.lon, np.zeros(n)),
            spd=put(actwp.spd, np.full(n, -999.0)),
            turndist=put(actwp.turndist, np.ones(n)),
            flyby=put(actwp.flyby, np.ones(n)),
            next_qdr=put(actwp.next_qdr, np.full(n, -999.0)),
            nextaltco=put(actwp.nextaltco, np.zeros(n)),
            xtoalt=put(actwp.xtoalt, np.zeros(n)))
        asas = asas.replace(trk=put(asas.trk, hdg), tas=put(asas.tas, tas),
                            alt=put(asas.alt, alt), vs=put(asas.vs, np.zeros(n)),
                            active=asas.active.at[idx].set(False))
        adsb = adsb.replace(lat=put(adsb.lat, lat), lon=put(adsb.lon, lon),
                            alt=put(adsb.alt, alt), trk=put(adsb.trk, hdg),
                            tas=put(adsb.tas, tas), gs=put(adsb.gs, tas),
                            lastupdate=put(adsb.lastupdate, np.zeros(n)))

        # Performance coefficients per type (perfoap.py:49-113)
        perf = st.perf
        cols = {}
        for k in range(n):
            vals = perf_coeffs.slot_values(self.coeffdb.get(types[k]))
            for name, v in vals.items():
                cols.setdefault(name, []).append(v)
        for name, v in cols.items():
            arr = getattr(perf, name)
            perf = perf.replace(**{name: arr.at[idx].set(
                jnp.asarray(np.asarray(v), arr.dtype))})

        # Route tables: clear the slots
        route = st.route
        route = route.replace(
            nwp=route.nwp.at[idx].set(0),
            iactwp=route.iactwp.at[idx].set(-1))

        self.state = st.replace(ac=ac, ap=ap, actwp=actwp, asas=asas,
                                adsb=adsb, perf=perf, route=route)
        self.trails.create(slots, lat, lon, t=float(st.simt))
        for hook in self.create_hooks:
            hook(slots)

    # ---------------------------------------------------------------- delete
    def delete(self, idx):
        """Deactivate slot(s); stable slot identity (cf. traffic.py:365-381)."""
        self.flush()
        if np.isscalar(idx):
            idx = [int(idx)]
        idx = [int(i) for i in np.atleast_1d(np.asarray(idx))]
        for i in idx:
            if self.ids[i] is not None:
                del self._id2slot[self.ids[i]]
                self.ids[i] = None
                self.types[i] = None
        st = self.state
        jidx = jnp.asarray(np.asarray(idx))
        ac = st.ac.replace(active=st.ac.active.at[jidx].set(False))
        # Clear any conflict-pair state involving the slot
        rp = st.asas.resopairs.at[jidx, :].set(False).at[:, jidx].set(False)
        # Clear the deleted aircraft's own partner rows AND every reference
        # to its slots in other rows — a freed slot can be reused by create()
        # before the next ASAS interval would have purged the stale entry.
        partners = st.asas.partners.at[jidx, :].set(-1)
        stale = jnp.isin(partners, jnp.asarray(jidx, jnp.int32))
        partners = jnp.where(stale, -1, partners)
        # Sorted-space table (sparse backend): the deleted caller slots
        # live at sort_perm[jidx] in the padded layout; purge those rows
        # and every value referencing them, for the same slot-reuse
        # reason as above.
        sidx = st.asas.sort_perm[jidx]
        partners_s = st.asas.partners_s.at[sidx, :].set(-1)
        stale_s = jnp.isin(partners_s, sidx.astype(jnp.int32))
        partners_s = jnp.where(stale_s, -1, partners_s)
        asas = st.asas.replace(resopairs=rp, partners=partners,
                               partners_s=partners_s,
                               active=st.asas.active.at[jidx].set(False))
        self.state = st.replace(ac=ac, asas=asas)
        for hook in self.delete_hooks:
            hook(idx)
        return True

    def reset(self):
        seed = int(self._rng.integers(0, 2**31 - 1))
        self.state = make_state(self.nmax, self.wmax, self.dtype, seed,
                                self.pair_matrix, self.k_partners)
        self.ids = [None] * self.nmax
        self.types = [None] * self.nmax
        self._id2slot = {}
        self._pending = []
        self._autoid = 0
        self.trails.reset()

    # ------------------------------------------------------------- creconfs
    def creconfs(self, acid, actype, targetidx, dpsi, cpa, tlosh,
                 dh=None, tlosv=None, spd=None,
                 pzr_nm=5.0, pzh_ft=1000.0):
        """Create an aircraft on a synthetic conflict course with target
        (reference traffic.py:314-363)."""
        self.flush()
        st = self.state
        getf = lambda a: float(np.asarray(a)[targetidx])
        latref, lonref = getf(st.ac.lat), getf(st.ac.lon)
        altref = getf(st.ac.alt)
        trkref = np.radians(getf(st.ac.trk))
        gsref = getf(st.ac.gs)
        vsref = getf(st.ac.vs)
        cpa_m = cpa * aero.nm
        pzr = pzr_nm * aero.nm
        pzh = pzh_ft * aero.ft

        trk = trkref + np.radians(dpsi)
        gs = gsref if spd is None else spd
        if dh is None:
            acalt = altref
            acvs = 0.0
        else:
            acalt = altref + dh
            tlosv = tlosh if tlosv is None else tlosv
            acvs = vsref - np.sign(dh) * (abs(dh) - pzh) / tlosv

        gsn, gse = gs * np.cos(trk), gs * np.sin(trk)
        vreln = gsref * np.cos(trkref) - gsn
        vrele = gsref * np.sin(trkref) - gse
        vrel = np.sqrt(vreln * vreln + vrele * vrele)
        drelcpa = tlosh * vrel + (0 if cpa_m > pzr
                                  else np.sqrt(pzr * pzr - cpa_m * cpa_m))
        dist = np.sqrt(drelcpa * drelcpa + cpa_m * cpa_m)
        rd = drelcpa / dist
        rx = cpa_m / dist
        brn = np.degrees(np.arctan2(-rx * vreln + rd * vrele,
                                    rd * vreln + rx * vrele))
        from ..ops import geo as jgeo
        aclat, aclon = (float(x) for x in
                        jgeo.qdrpos(jnp.float64(latref) if self.dtype == jnp.float64
                                    else jnp.asarray(latref, self.dtype),
                                    jnp.asarray(lonref, self.dtype),
                                    jnp.asarray(brn, self.dtype),
                                    jnp.asarray(dist / aero.nm, self.dtype)))
        acspd = float(_np_vtas2cas(np.hypot(gsn, gse), acalt))
        achdg = float(np.degrees(np.arctan2(gse, gsn)))
        self.create(1, actype, acalt, acspd, None, aclat, aclon, achdg, acid)
        self.flush()
        s = self._id2slot[acid.upper()]
        st = self.state
        self.state = st.replace(ac=st.ac.replace(
            vs=st.ac.vs.at[s].set(acvs),
            selalt=st.ac.selalt.at[s].set(altref),
            selvs=st.ac.selvs.at[s].set(acvs)))


# --- Host-side NumPy twins of the aero conversions used at creation time ----
# (creation happens on host with float64; the device versions live in
# ops/aero.py — same formulas, reference aero.py:62-168)

def _np_vatmos(h):
    T = np.maximum(288.15 - 0.0065 * h, 216.65)
    rhotrop = 1.225 * (T / 288.15) ** 4.256848030018761
    dhstrat = np.maximum(0.0, h - 11000.0)
    rho = rhotrop * np.exp(-dhstrat / 6341.552161)
    return rho * 287.05287 * T, rho, T


def _np_vtas2cas(tas, h):
    p, rho, _ = _np_vatmos(h)
    qdyn = p * ((1.0 + rho * tas * tas / (7.0 * p)) ** 3.5 - 1.0)
    cas = np.sqrt(7.0 * aero.p0 / aero.rho0
                  * ((qdyn / aero.p0 + 1.0) ** (2.0 / 7.0) - 1.0))
    return np.where(tas < 0, -cas, cas)


def _np_vcas2tas(cas, h):
    p, rho, _ = _np_vatmos(h)
    qdyn = aero.p0 * ((1.0 + aero.rho0 * cas * cas / (7.0 * aero.p0)) ** 3.5 - 1.0)
    tas = np.sqrt(7.0 * p / rho * ((1.0 + qdyn / p) ** (2.0 / 7.0) - 1.0))
    return np.where(cas < 0, -tas, tas)


def _np_vcasormach(spd, h):
    a = np.sqrt(1.4 * 287.05287 * np.maximum(288.15 - 0.0065 * h, 216.65))
    ismach = (0.1 < spd) & (spd < 1.0)
    tas = np.where(ismach, spd * a, _np_vcas2tas(spd, h))
    cas = np.where(ismach, _np_vtas2cas(tas, h), spd)
    mach = np.where(ismach, spd, tas / a)
    return tas, cas, mach
