"""Airborne Separation Assurance: device-side CD&R coordinator.

Parity with the reference ASAS coordinator (``bluesky/traffic/asas/asas.py``):
per-interval conflict detection -> resolution -> pair bookkeeping ->
resume-navigation recovery (asas.py:473-504, 409-471), with protected-zone
radii/margins and resolver configuration.

TPU-first: the reference keeps conflict pairs as Python lists/sets of
callsign tuples and loops over them.  Here the whole update is jitted: the
pair state is the [N,N] ``resopairs`` matrix, bookkeeping is boolean algebra,
and the conflict/LoS *counts* are device scalars.  Host-side code (stack
commands CONF/LOS lists, logging) extracts pair lists lazily via
``ops.cd.pairs_from_mask`` only when asked.

Resolver selection: MVP is the default (and currently only) device resolver;
the registry hook mirrors the reference's CDmethods/CRmethods dicts
(asas.py:41-55) for host-side extension.
"""
import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import aero, cd as cdops, cd_tiled, cr_mvp
from ..ops.cd import ConflictData
from ..ops.cd_tiled import RowConflictData
from .state import SimState


class AsasConfig(NamedTuple):
    """ASAS settings (reference asas.py:10-13 defaults + setters).

    Static under jit: toggling recompiles (cached per configuration), which
    matches how rarely these change vs how hot the step loop is.
    """
    swasas: bool = True
    dtasas: float = 1.0          # [s] CD&R interval
    dtlookahead: float = 300.0   # [s]
    rpz: float = 5.0 * aero.nm   # [m] protected-zone radius (R)
    hpz: float = 1000.0 * aero.ft  # [m] protected-zone half-height (dh)
    mar: float = 1.05            # resolution margin factor
    resofach: float = 1.05       # horizontal resolution factor (Rm = R*fac)
    resofacv: float = 1.05       # vertical resolution factor
    swresohoriz: bool = False
    swresospd: bool = False
    swresohdg: bool = False
    swresovert: bool = False
    reso_on: bool = True         # conflict resolution enabled (RESO MVP/OFF)
    reso_method: str = "MVP"     # MVP / EBY / SWARM / SSD (CRmethods
                                 # registry, asas.py:41-55); static under
                                 # jit like the rest of the config
    swprio: bool = False         # PRIORULES on/off (asas.py SetPrio)
    priocode: str = "FF1"        # FF1/FF2/FF3/LAY1/LAY2
    sort_every: int = 30         # tiled backends: CD intervals between
                                 # Morton re-sorts (any staleness is exact —
                                 # see AsasArrays.sort_perm)
    vmin: float = 100.0 * aero.kts   # [m/s] resolution speed caps
    vmax: float = 180.0 * aero.kts   # (reference asas.py setters)
    vsmin: float = -3000.0 * aero.fpm
    vsmax: float = 3000.0 * aero.fpm

    @property
    def rpz_m(self):
        return self.rpz * self.resofach

    @property
    def hpz_m(self):
        return self.hpz * self.resofacv


def update(state: SimState, cfg: AsasConfig,
           smooth=None) -> Tuple[SimState, ConflictData]:
    """One ASAS interval: detect, resolve, bookkeep, resume (asas.py:473-504).

    ``smooth`` (diff.smooth.SmoothConfig; None on the serving path)
    engages the differentiable-mode relaxations: the hard conflict
    indicator becomes sigmoid pair weights on the MVP contribution sums
    (``soft_conflict_weight``), the resolver's min reduction a softmin,
    and the velocity caps straight-through clips.  The per-aircraft
    engagement *selection* (``upd``/``active`` gating below) stays
    hard-forward — both branches of each ``jnp.where`` are
    differentiable, and the gradient signal rides the smooth weights.
    MVP is the differentiable resolver; the other methods raise.
    """
    ac, asas = state.ac, state.asas

    cd = cdops.detect(ac.lat, ac.lon, ac.trk, ac.gs, ac.alt, ac.vs,
                      ac.active, cfg.rpz, cfg.hpz, cfg.dtlookahead)

    if smooth is not None and cfg.reso_on \
            and cfg.reso_method.upper() != "MVP":
        raise ValueError(
            "differentiable mode (SimConfig.smooth) relaxes the MVP "
            f"resolver only, not {cfg.reso_method!r} — use RESO MVP "
            "(or RESO OFF) for gradient workloads.")

    wconf = None
    if smooth is not None:
        from ..diff import smooth as smoothmod
        wconf = smoothmod.soft_conflict_weight(
            cd, cfg.rpz, cfg.dtlookahead, smooth)

    if cfg.reso_on:
        mvpcfg = cr_mvp.MVPConfig(
            rpz_m=cfg.rpz_m, hpz_m=cfg.hpz_m, tlookahead=cfg.dtlookahead,
            swresohoriz=cfg.swresohoriz, swresospd=cfg.swresospd,
            swresohdg=cfg.swresohdg, swresovert=cfg.swresovert,
            swprio=cfg.swprio, priocode=cfg.priocode)
        method = cfg.reso_method.upper()
        if method in ("MVP", "SWARM"):
            newtrk, newgs, newvs, newalt, asase, asasn = cr_mvp.resolve(
                cd, ac.alt, ac.gseast, ac.gsnorth, ac.vs, ac.trk, ac.gs,
                ac.selalt, state.ap.vs, asas.alt,
                cfg.vmin, cfg.vmax, cfg.vsmin, cfg.vsmax, mvpcfg,
                noreso=asas.noreso, resooff=asas.resooff,
                wconf=wconf, smooth=smooth)
        if method == "EBY":
            from ..ops import cr_eby
            newtrk, newgs, newvs, newalt = cr_eby.resolve(
                cd, ac.alt, ac.vs, ac.trk, ac.tas,
                cfg.rpz_m, cfg.vmin, cfg.vmax)
            asase = newgs * jnp.sin(jnp.radians(newtrk))
            asasn = newgs * jnp.cos(jnp.radians(newtrk))
        elif method == "SWARM":
            from ..ops import cr_swarm
            # Swarm blends the MVP output computed above with alignment
            # and flock centering (Swarm.py:68-110).  The CA gate is the
            # PREVIOUS interval's active flags — the resume-nav
            # hysteresis output, which is what asas.active holds at
            # reference resolve time (Swarm.py:70-73).
            # selspd may hold a Mach number; resolve to CAS like the
            # autopilot does (the reference Swarm blends raw selspd,
            # Swarm.py:72 — a unit bug upstream, fixed here)
            _, selcas, _ = aero.vcasormach(ac.selspd, ac.alt)
            newtrk, newgs, newvs, newalt = cr_swarm.resolve(
                cd, ac.lat, ac.lon, ac.alt, ac.trk, ac.gs, ac.cas,
                ac.vs, ac.gseast, ac.gsnorth, ac.active,
                newtrk, newgs, newvs, asas.active,
                state.ap.trk, selcas, ac.selvs,
                cfg.vmin, cfg.vmax)
            asase = newgs * jnp.sin(jnp.radians(newtrk))
            asasn = newgs * jnp.cos(jnp.radians(newtrk))
        elif method == "SSD":
            from ..ops import cr_ssd
            # PRIORULES RS1..RS9 select the SSD ruleset (reference
            # SSD.py:429-558); non-RS priocodes (the MVP FF*/LAY* family)
            # fall back to the RS1 default like the reference's separate
            # registries do.
            rs = cfg.priocode.upper() if cfg.swprio \
                and cfg.priocode.upper().startswith("RS") else "RS1"
            ssdcfg = cr_ssd.SSDConfig(rpz_m=cfg.rpz_m,
                                      tlookahead=cfg.dtlookahead,
                                      priocode=rs)
            newtrk, newgs = cr_ssd.resolve(
                cd, ac.lat, ac.lon, ac.alt, ac.trk, ac.gs, ac.vs,
                ac.gseast, ac.gsnorth, ac.active,
                cfg.vmin, cfg.vmax, ssdcfg, hdg=ac.hdg,
                ap_trk=state.ap.trk, ap_tas=state.ap.tas)
            # SSD is a horizontal method (SSD.py:99-104)
            newvs, newalt = asas.vs, asas.alt
            asase = newgs * jnp.sin(jnp.radians(newtrk))
            asasn = newgs * jnp.cos(jnp.radians(newtrk))
        elif method != "MVP":
            raise ValueError(
                f"Unknown AsasConfig.reso_method {cfg.reso_method!r}; "
                "expected MVP, EBY, SWARM or SSD.")
        # Swarm commands apply to the whole swarm once any conflict
        # exists (the reference only calls resolve when confpairs is
        # non-empty, asas.py:487, and Swarm then sets all active);
        # others gate on inconf.  Non-updated aircraft keep the previous
        # resolution state (the reference overwrites all, but only
        # `active` aircraft consume them — keeping them avoids NaN
        # leakage from padding garbage).
        if method == "SWARM":
            upd = ac.active & jnp.any(cd.swconfl)
        else:
            upd = cd.inconf
        asas = asas.replace(
            trk=jnp.where(upd, newtrk, asas.trk),
            tas=jnp.where(upd, newgs, asas.tas),
            vs=jnp.where(upd, newvs, asas.vs),
            alt=jnp.where(upd, newalt, asas.alt),
            asase=jnp.where(upd, asase, asas.asase),
            asasn=jnp.where(upd, asasn, asas.asasn))

    # Pair bookkeeping (asas.py:489-502): resopairs accumulates conflicts
    resopairs = asas.resopairs | cd.swconfl

    # ResumeNav (asas.py:409-471)
    resopairs, active = cr_mvp.resume_nav(
        resopairs, cd.swlos, ac.lat, ac.lon, ac.gseast, ac.gsnorth, ac.trk,
        ac.active, cfg.rpz, cfg.rpz * cfg.resofach)

    if cfg.reso_on and cfg.reso_method.upper() == "SWARM":
        # The whole swarm follows ASAS, not only conflict pairs — but
        # only once any conflict triggered a resolve (asas.py:487 gate +
        # Swarm.py:101-102 active.fill(True))
        active = jnp.where(jnp.any(cd.swconfl), ac.active, active)

    asas = asas.replace(
        resopairs=resopairs,
        active=active & cfg.reso_on,
        inconf=cd.inconf,
        tcpamax=cd.tcpamax,
        nconf_cur=jnp.sum(cd.swconfl, dtype=jnp.int32),
        nlos_cur=jnp.sum(cd.swlos, dtype=jnp.int32))
    return state.replace(asas=asas), cd


def detect_only(state: SimState, cfg: AsasConfig):
    """CD without resolution (RESO OFF path) — still updates flags/counts."""
    ac = state.ac
    cd = cdops.detect(ac.lat, ac.lon, ac.trk, ac.gs, ac.alt, ac.vs,
                      ac.active, cfg.rpz, cfg.hpz, cfg.dtlookahead)
    asas = state.asas.replace(
        inconf=cd.inconf, tcpamax=cd.tcpamax,
        nconf_cur=jnp.sum(cd.swconfl, dtype=jnp.int32),
        nlos_cur=jnp.sum(cd.swlos, dtype=jnp.int32))
    return state.replace(asas=asas), cd


def impl_for_backend(cd_backend: str) -> str:
    """SimConfig.cd_backend -> update_tiled/refresh_spatial_sort impl."""
    return {"pallas": "pallas", "sparse": "sparse"}.get(cd_backend, "lax")


@functools.partial(jax.jit,
                   static_argnames=("block", "tlookahead", "rpz"))
def _sparse_sort_refresh(lat, lon, gs, alt, vs, active, old_perm,
                         partners_s, *, block, tlookahead, rpz):
    """The sparse refresh as ONE compiled program.  Measured eager on
    the v5e tunnel this chain of ~30 host-dispatched ops cost 600 ms
    per refresh (12 ms/sim-s amortized at the 1000-step protocol —
    16% of the whole interval); jitted it is a single dispatch."""
    from ..ops import cd_sched
    thresh = cd_sched.reach_threshold_m(gs, active, tlookahead, rpz)
    # Altitude layering stays OFF: measured end-to-end on the v5e at
    # N=100k it loses ~4% even on the dense 230 nm circle (1.74x vs
    # 1.82x real-time) — the schedule-level 2.3x pair reduction is
    # real, but the regional wall time is dominated by per-pair
    # conflict tails (2.5M concurrent conflicts), and the real fleet's
    # TAS spread fattens the layered blocks.  The mechanism remains
    # available (stripe_sort_dest n_layers, incl. the on-device "auto"
    # gate) for fleets with genuinely banded cruise altitudes.
    dest = cd_sched.stripe_sort_dest(
        lat, lon, gs, active, thresh, block, 32,
        alt=alt, vs=vs).astype(jnp.int32)
    # Remap the sorted-space partner table old-layout -> new-layout:
    # old slot -> caller slot (inverse of the old dest) -> new slot.
    # Costs a few [n_tot,K] gathers ONCE per refresh — amortized over
    # sort_every intervals, vs. per-interval gathers if the table
    # lived in caller space.
    n = lat.shape[0]
    n_tot = cd_sched.padded_size(n, block)
    inv_old = cd_sched.slot_inverse(old_perm, n, n_tot)
    pv = partners_s[:n_tot]
    caller_vals = jnp.where(
        pv >= 0, inv_old[jnp.clip(pv, 0, n_tot)], -1)
    new_vals = jnp.where(
        caller_vals >= 0,
        dest[jnp.clip(caller_vals, 0, n - 1)], -1)
    per_caller = new_vals[jnp.clip(old_perm, 0, n_tot - 1), :]   # [n, K]
    spad = partners_s.shape[0]
    new_partners = jnp.full((spad, pv.shape[1]), -1,
                            jnp.int32).at[dest].set(per_caller)
    return dest, new_partners


def _rebucket_callers(active, dest0, dev, n, n_tot, ndev, C):
    """Caller-slot re-bucketing shared by the stripe and tile refreshes
    (a full [n] bijection): device d's caller shard [d*C, (d+1)*C) gets
    exactly the active aircraft whose sorted slots d owns (packed in
    sorted order), inactive rows fill the per-shard tails.  Returns
    ``(newslot [n], src [n], counts [ndev])`` — counts <= C is the
    caller's occupancy contract to check."""
    aidx = jnp.arange(n, dtype=jnp.int32)
    key = jnp.where(active, dest0, n_tot + aidx)   # actives first, by slot
    order = jnp.argsort(key)
    act_o = active[order]
    dev_o = dev[order]
    oh = (dev_o[:, None] == jnp.arange(ndev, dtype=jnp.int32)[None, :]) \
        & act_o[:, None]
    counts = jnp.sum(oh, axis=0, dtype=jnp.int32)          # [ndev]
    rank_o = jnp.sum((jnp.cumsum(oh, axis=0) - 1) * oh, axis=1)
    slot_act_o = dev_o * C + rank_o
    # free caller slots (per-shard tails) in ascending order for the
    # inactive fillers; counts <= C is checked by the host caller
    free = (aidx % C) >= counts[jnp.minimum(aidx // C, ndev - 1)]
    free_slots = jnp.sort(jnp.where(free, aidx, n))
    n_act = jnp.sum(active, dtype=jnp.int32)
    inact_rank = jnp.clip(aidx - n_act, 0, n - 1)
    newslot_o = jnp.where(act_o, slot_act_o,
                          free_slots[inact_rank]).astype(jnp.int32)
    newslot = jnp.zeros((n,), jnp.int32).at[order].set(newslot_o)
    src = jnp.zeros((n,), jnp.int32).at[newslot].set(aidx)
    return newslot, src, counts


def _remap_partners_sorted(old_perm, partners_s, active, dest0,
                           dest_sent, n, n_tot):
    """Sorted-space partner-table remap old layout -> new layout (old
    sorted -> old caller -> new sorted), shared by the stripe and tile
    refreshes — same chain as ``_sparse_sort_refresh`` plus the caller
    migration, which cancels out because the table is keyed in sorted
    space."""
    from ..ops import cd_sched
    inv_old = cd_sched.slot_inverse(old_perm, n, n_tot)
    pv = partners_s[:n_tot]
    caller_vals = jnp.where(pv >= 0, inv_old[jnp.clip(pv, 0, n_tot)], -1)
    cv = jnp.clip(caller_vals, 0, n - 1)
    new_vals = jnp.where((caller_vals >= 0) & active[cv],
                         dest0[cv], -1)
    row_ok = (old_perm < n_tot) & active
    per_caller = jnp.where(row_ok[:, None],
                           new_vals[jnp.clip(old_perm, 0, n_tot - 1), :],
                           -1)
    return jnp.full((n_tot, pv.shape[1]), -1, jnp.int32) \
        .at[dest_sent].set(per_caller, mode="drop")


@functools.partial(jax.jit, static_argnames=(
    "block", "ndev", "extra", "halo", "tlookahead", "rpz",
    "min_reach_m", "margin_s"))
def _spatial_shard_refresh(lat, lon, gs, alt, vs, active, old_perm,
                           partners_s, *, block, ndev, extra, halo,
                           tlookahead, rpz, min_reach_m, margin_s):
    """Spatial-mode sort refresh: stripe sort + device RE-BUCKETING as
    one compiled program.

    Unlike ``_sparse_sort_refresh`` (which only moves aircraft between
    SORTED slots), the spatial mode also migrates aircraft between
    CALLER slots so that caller shard d of the device mesh holds
    exactly the aircraft whose sorted latitude-stripe slots device d
    owns — the invariant that makes the per-interval padded scatter and
    result back-map device-local (zero per-interval O(N) collectives,
    ops/cd_sched.py spatial branch).  Inactive rows fill the per-shard
    gaps and carry the SENTINEL sort slot ``n_tot`` (dropped from the
    scatter; their results read the accumulator identities).

    Returns ``(newslot, src, sort_perm_new, partners_new, stats)``:

    * ``newslot`` [n]: old caller slot -> new caller slot (the host
      applies it to ids/routes/conditions via
      ``Traffic.apply_slot_permutation``),
    * ``src`` [n]: new caller slot -> old caller slot (gather index for
      permuting every [n]-leading state leaf),
    * ``sort_perm_new`` [n]: new caller slot -> sorted slot (sentinel
      ``n_tot`` on inactive rows),
    * ``partners_new`` [n_tot, K]: the sorted-space partner table
      remapped old layout -> new layout (old sorted -> old caller ->
      new sorted),
    * ``stats``: ``(counts [ndev], halo_ok, halo_need, gsmax)`` —
      per-device active occupancy, whether the ``halo``-block window
      covers every reachable block pair even after ``margin_s`` seconds
      of worst-case drift (the exact conservative
      rpz + lookahead*(gs_i+gs_j) bound, horizontally widened by
      2*gsmax*margin_s), and the widest halo actually needed.
    """
    from ..ops import cd_sched
    n = lat.shape[0]
    nb = -(-n // block) + extra
    n_tot = nb * block
    nb_l = nb // ndev
    S = nb_l * block
    C = n // ndev
    thresh = cd_sched.reach_threshold_m(gs, active, tlookahead, rpz)
    dest0 = cd_sched.stripe_sort_dest(
        lat, lon, gs, active, thresh, block, extra,
        alt=alt, vs=vs, spread_pad=True).astype(jnp.int32)
    dev = jnp.minimum(dest0 // S, ndev - 1)
    newslot, src, counts = _rebucket_callers(
        active, dest0, dev, n, n_tot, ndev, C)
    dest_sent = jnp.where(active, dest0, n_tot)
    sort_perm_new = dest_sent[src]
    partners_new = _remap_partners_sorted(
        old_perm, partners_s, active, dest0, dest_sent, n, n_tot)

    # ---- halo coverage check, drift-margin widened ----
    pcols = cd_sched.scatter_padded(
        [lat, lon, gs, active.astype(lat.dtype)], dest_sent, n_tot)
    plat, plon, pgs, pact = pcols
    summ = cd_tiled.block_summaries(plat, plon, pgs, pact > 0.5,
                                    nb, block)
    gsmax = jnp.max(jnp.where(active, gs, 0.0))
    # min_reach_m: the interval's schedule widens reachability to the
    # SWARM neighbourhood radius (cd_sched min_reach_m=R_SWARM), so the
    # coverage check must validate the SAME widened bound — and it
    # applies no vertical gating at all, so its reach is a superset of
    # the interval's vertically-gated one for any min_vreach_m.
    reach_m = cd_tiled.reachability_from_summaries(
        summ, summ, float(rpz), float(tlookahead),
        min_reach_m=float(min_reach_m),
        margin_m=2.0 * gsmax * margin_s)
    bi = jnp.arange(nb, dtype=jnp.int32)
    d_i = bi // nb_l
    lo = d_i * nb_l - halo
    hi = (d_i + 1) * nb_l + halo
    outside = (bi[None, :] < lo[:, None]) | (bi[None, :] >= hi[:, None])
    halo_ok = ~jnp.any(reach_m & outside)
    # widest halo the current geometry would need (readback/diagnosis):
    # blocks past the owning device's own range, over reachable pairs
    need = jnp.maximum(jnp.maximum(
        (d_i * nb_l)[:, None] - bi[None, :],
        bi[None, :] - ((d_i + 1) * nb_l)[:, None] + 1), 0)
    halo_need = jnp.max(jnp.where(reach_m, need, 0))
    return newslot, src, sort_perm_new, partners_new, \
        (counts, halo_ok, halo_need, gsmax)


_morton_perm_jit = jax.jit(
    lambda lat, lon, active: cd_tiled.spatial_permutation(
        lat, lon, active).astype(jnp.int32))


def refresh_spatial_sort(state: SimState, cfg: AsasConfig,
                         block: int = 512, impl: str = "lax") -> SimState:
    """Recompute the cached spatial sort for the tiled/pallas/sparse
    backends.  HOST-called at chunk boundaries, deliberately outside the
    jitted step (see the note in ``update_tiled``); cadence is the
    caller's (Simulation refreshes every ``cfg.sort_every`` CD intervals
    of sim time, bench once per scan chunk) — any staleness is exact.
    The compute itself is one jitted program per flavor (an eager chain
    here costs hundreds of ms through the TPU tunnel)."""
    ac = state.ac
    if impl == "sparse":
        dest, partners_s = _sparse_sort_refresh(
            ac.lat, ac.lon, ac.gs, ac.alt, ac.vs, ac.active,
            state.asas.sort_perm, state.asas.partners_s,
            block=min(block, 256), tlookahead=float(cfg.dtlookahead),
            rpz=float(cfg.rpz))
        return state.replace(asas=state.asas.replace(
            sort_perm=dest, partners_s=partners_s))
    perm = _morton_perm_jit(ac.lat, ac.lon, ac.active)
    return state.replace(asas=state.asas.replace(sort_perm=perm))


def refresh_spatial_shard(state: SimState, cfg: AsasConfig, ndev: int,
                          block: int = 256, halo_blocks: int = 0):
    """Spatial-mode chunk-edge refresh: stripe sort, caller-slot
    re-bucketing, partner remap and the halo-coverage check as one
    jitted program, then the state permutation applied host-side.

    Returns ``(state, newslot, stats)`` — ``newslot`` is the
    old-caller -> new-caller slot map as a numpy array (the caller
    remaps ids/routes/conditions with it,
    ``Traffic.apply_slot_permutation``), ``stats`` a dict with the
    per-device occupancy, halo coverage flag and needed halo width.

    Raises ``RuntimeError`` when the geometry cannot satisfy the
    spatial contract — a device's stripe population exceeding its
    caller-shard capacity (QarSUMO-style partition imbalance), or
    reachability crossing more than the halo window even after the
    drift margin — instead of silently risking missed conflicts; the
    caller falls back to the column-replicated mode (or a wider halo).
    """
    from ..ops import cd_sched
    ac = state.ac
    n = ac.lat.shape[0]
    block = min(block, 256)
    extra, nb, nb_l, n_tot = cd_sched.spatial_layout(n, block, ndev)
    if state.asas.partners_s.shape[0] < n_tot:
        raise RuntimeError(
            f"spatial refresh: partners_s holds "
            f"{state.asas.partners_s.shape[0]} rows < n_tot={n_tot} — "
            "enable spatial mode first (it resizes the sorted tables)")
    halo_max = (ndev - 1) * nb_l           # multi-hop exchange ceiling
    # halo_blocks == 0 -> AUTO: check coverage against the widest
    # possible window, then pin 1.25x the measured need (>= one
    # device) so drift headroom survives between refreshes; the caller
    # stores the pinned width in SimConfig.cd_halo_blocks so every
    # interval compiles against the same static window.
    auto = not halo_blocks
    halo = halo_max if auto else min(int(halo_blocks), halo_max)
    # The interval's schedule widens reachability to the SWARM
    # neighbourhood radius; validate halo coverage against the same
    # widened bound (cd_sched.detect_resolve_sched's min_reach).
    min_reach = 0.0
    if cfg.reso_on and cfg.reso_method.upper() == "SWARM":
        from ..ops import cr_swarm
        min_reach = float(cr_swarm.R_SWARM)
    newslot, srcidx, sort_perm, partners_new, stats = \
        _spatial_shard_refresh(
            ac.lat, ac.lon, ac.gs, ac.alt, ac.vs, ac.active,
            state.asas.sort_perm, state.asas.partners_s[:n_tot],
            block=block, ndev=int(ndev), extra=extra, halo=halo,
            tlookahead=float(cfg.dtlookahead), rpz=float(cfg.rpz),
            min_reach_m=min_reach,
            margin_s=float(cfg.sort_every * cfg.dtasas))
    counts, halo_ok, halo_need, gsmax = stats
    if auto:
        halo = min(max(nb_l, int(np.ceil(1.25 * int(halo_need)))),
                   halo_max)
    counts = np.asarray(counts)
    C = n // ndev
    if counts.max() > C:
        raise RuntimeError(
            f"spatial refresh: stripe occupancy overflow — device "
            f"{int(counts.argmax())} owns {int(counts.max())} aircraft "
            f"> caller-shard capacity {C} (nmax/{ndev}). Raise nmax or "
            "use SHARD REPLICATE for this geometry.")
    if not bool(halo_ok):
        raise RuntimeError(
            f"spatial refresh: halo coverage violated — reachability "
            f"(drift-margin widened) needs {int(halo_need)} halo blocks "
            f"> {halo} available per side. Use SHARD REPLICATE or fewer "
            "devices for this geometry.")

    def permute(leaf):
        if hasattr(leaf, "ndim") and leaf.ndim >= 1 \
                and leaf.shape[0] == n:
            return leaf[srcidx]
        return leaf
    new_state = jax.tree.map(permute, state)
    asas_new = new_state.asas
    # caller-space partner ids (tiled path) move WITH the slots
    p = asas_new.partners
    p = jnp.where(p >= 0, newslot[jnp.clip(p, 0, n - 1)], -1)
    spad = state.asas.partners_s.shape[0] - n_tot
    if spad > 0:
        partners_new = jnp.concatenate(
            [partners_new,
             jnp.full((spad, partners_new.shape[1]), -1, jnp.int32)])
    new_state = new_state.replace(asas=asas_new.replace(
        sort_perm=sort_perm, partners_s=partners_new, partners=p))
    info = dict(counts=counts, occupancy=float(counts.max() / max(C, 1)),
                halo_blocks=halo, halo_need=int(halo_need),
                gsmax=float(gsmax), nb=nb, nb_local=nb_l, n_tot=n_tot,
                extra_blocks=extra,
                halo_rows=2 * halo * block * ndev)
    return new_state, np.asarray(newslot), info


@functools.partial(jax.jit, static_argnames=(
    "block", "extra", "tiles", "budgets", "tlookahead", "rpz",
    "min_reach_m", "margin_s"))
def _tile_shard_refresh(lat, lon, gs, alt, vs, active, old_perm,
                        partners_s, *, block, extra, tiles, budgets,
                        tlookahead, rpz, min_reach_m, margin_s):
    """Tiles-mode sort refresh: 2-D tile-major sort + device
    re-bucketing + the corner-halo contract validation as one compiled
    program — the lat x lon generalisation of
    ``_spatial_shard_refresh`` (same return structure, same caller-slot
    bijection and partner-remap shapes).

    Validation replaces the stripe window check with TWO conditions on
    the drift-margin-widened reachability: (1) every reachable block
    pair stays inside the canonical edge+corner neighbourhood of
    ``cd_sched.tile_offsets`` (a reach escaping it could not be shipped
    by the per-offset exchange at all), and (2) with ``budgets`` given,
    each offset's measured per-receiver import need fits its pinned
    slab budget.  Because the interval's exports select from the SAME
    (unwidened) reachability, margin-widened need >= interval need —
    so a passing refresh guarantees no conflict pair can be missed
    until the next one.

    ``stats`` is ``(counts [ndev], halo_ok, budget_ok, needs [n_offs],
    gsmax)`` — needs are the measured per-offset import-block maxima
    (the host pins budgets at 1.25x these in auto mode).
    """
    from ..ops import cd_sched
    n = lat.shape[0]
    nb = -(-n // block) + extra
    n_tot = nb * block
    tR, tC = int(tiles[0]), int(tiles[1])
    ndev = tR * tC
    nb_t = nb // ndev
    S = nb_t * block
    C = n // ndev
    thresh = cd_sched.reach_threshold_m(gs, active, tlookahead, rpz)
    dest0 = cd_sched.tile_sort_dest(
        lat, lon, gs, active, thresh, block, extra, (tR, tC),
        alt=alt, vs=vs).astype(jnp.int32)
    dev = jnp.minimum(dest0 // S, ndev - 1)
    newslot, src, counts = _rebucket_callers(
        active, dest0, dev, n, n_tot, ndev, C)
    dest_sent = jnp.where(active, dest0, n_tot)
    sort_perm_new = dest_sent[src]
    partners_new = _remap_partners_sorted(
        old_perm, partners_s, active, dest0, dest_sent, n, n_tot)

    # ---- corner-halo contract check, drift-margin widened ----
    pcols = cd_sched.scatter_padded(
        [lat, lon, gs, active.astype(lat.dtype)], dest_sent, n_tot)
    plat, plon, pgs, pact = pcols
    summ = cd_tiled.block_summaries(plat, plon, pgs, pact > 0.5,
                                    nb, block)
    gsmax = jnp.max(jnp.where(active, gs, 0.0))
    reach_m = cd_tiled.reachability_from_summaries(
        summ, summ, float(rpz), float(tlookahead),
        min_reach_m=float(min_reach_m),
        margin_m=2.0 * gsmax * margin_s)
    # column need per RECEIVER tile: any of tile v's rows reaching col b
    cn_t = jnp.any(reach_m.reshape(ndev, nb_t, nb), axis=1) \
        .reshape(ndev, ndev, nb_t)            # [recv, src tile, nb_t]
    treach = jnp.any(cn_t, axis=2)                         # [recv, src]
    offs = cd_sched.tile_offsets((tR, tC))
    allowed = np.eye(ndev, dtype=bool)
    for off in offs:
        for u, v in cd_sched._offset_pairs((tR, tC), off):
            allowed[v, u] = True               # v imports from sender u
    halo_ok = ~jnp.any(treach & ~jnp.asarray(allowed))
    needs = []
    for off in offs:
        uv = np.full(ndev, -1, np.int32)
        for u, v in cd_sched._offset_pairs((tR, tC), off):
            uv[v] = u
        cnt = jnp.sum(
            cn_t[jnp.arange(ndev), jnp.maximum(uv, 0)],
            axis=-1, dtype=jnp.int32)                      # [recv]
        needs.append(jnp.max(jnp.where(jnp.asarray(uv >= 0), cnt, 0)))
    needs = jnp.stack(needs)
    if budgets:
        budget_ok = jnp.all(
            needs <= jnp.asarray(budgets, jnp.int32))
    else:
        budget_ok = jnp.asarray(True)
    return newslot, src, sort_perm_new, partners_new, \
        (counts, halo_ok, budget_ok, needs, gsmax)


def refresh_tile_shard(state: SimState, cfg: AsasConfig, tiles,
                       block: int = 256, budgets=()):
    """Tiles-mode chunk-edge refresh: 2-D tile sort, caller-slot
    re-bucketing, partner remap and the corner-halo contract check as
    one jitted program, then the state permutation applied host-side —
    the lat x lon counterpart of ``refresh_spatial_shard``.

    ``budgets`` = () is AUTO: validate the neighbourhood contract, then
    pin each canonical offset's slab budget at 1.25x its measured need
    (>= 4 blocks drift headroom, <= the whole tile) — the caller stores
    the pinned tuple in SimConfig.cd_tile_budgets so every interval
    compiles against the same static exchange.

    Raises ``RuntimeError`` on a tile occupancy overflow (a tile's
    population exceeding its caller-shard capacity), on reachability
    escaping the edge+corner neighbourhood, or on a pinned budget
    falling short of the measured need — never silently misses
    conflicts; the caller falls back (tiles -> spatial -> replicate).
    """
    from ..ops import cd_sched
    ac = state.ac
    n = ac.lat.shape[0]
    block = min(block, 256)
    tR, tC = int(tiles[0]), int(tiles[1])
    ndev = tR * tC
    extra, nb, nb_t, n_tot = cd_sched.spatial_layout(n, block, ndev)
    if state.asas.partners_s.shape[0] < n_tot:
        raise RuntimeError(
            f"tile refresh: partners_s holds "
            f"{state.asas.partners_s.shape[0]} rows < n_tot={n_tot} — "
            "enable tiles mode first (it resizes the sorted tables)")
    min_reach = 0.0
    if cfg.reso_on and cfg.reso_method.upper() == "SWARM":
        from ..ops import cr_swarm
        min_reach = float(cr_swarm.R_SWARM)
    auto = not budgets
    budgets = tuple(int(b) for b in budgets) if budgets else ()
    newslot, srcidx, sort_perm, partners_new, stats = \
        _tile_shard_refresh(
            ac.lat, ac.lon, ac.gs, ac.alt, ac.vs, ac.active,
            state.asas.sort_perm, state.asas.partners_s[:n_tot],
            block=block, extra=extra, tiles=(tR, tC), budgets=budgets,
            tlookahead=float(cfg.dtlookahead), rpz=float(cfg.rpz),
            min_reach_m=min_reach,
            margin_s=float(cfg.sort_every * cfg.dtasas))
    counts, halo_ok, budget_ok, needs, gsmax = stats
    counts = np.asarray(counts)
    needs = np.asarray(needs)
    C = n // ndev
    if counts.max() > C:
        t_bad = int(counts.argmax())
        raise RuntimeError(
            f"tile refresh: tile occupancy overflow — tile "
            f"({t_bad // tC},{t_bad % tC}) owns {int(counts.max())} "
            f"aircraft > caller-shard capacity {C} (nmax/{ndev}). Raise "
            "nmax, use a different tile shape, or SHARD "
            "SPATIAL/REPLICATE for this geometry.")
    if not bool(halo_ok):
        raise RuntimeError(
            f"tile refresh: corner-halo contract violated — "
            f"(drift-margin widened) reachability escapes the "
            f"edge+corner neighbourhood of the {tR}x{tC} tile mesh. "
            "Use SHARD SPATIAL/REPLICATE or fewer tiles for this "
            "geometry.")
    if not bool(budget_ok):
        raise RuntimeError(
            f"tile refresh: halo slab budget exceeded — measured "
            f"per-offset import need {needs.tolist()} > pinned budgets "
            f"{list(budgets)}. Re-run SHARD TILE {tR}x{tC} to re-pin, "
            "or SHARD SPATIAL/REPLICATE for this geometry.")
    if auto:
        budgets = tuple(
            int(min(max(4, -(-int(nd) * 5 // 4)), nb_t))
            for nd in needs)

    def permute(leaf):
        if hasattr(leaf, "ndim") and leaf.ndim >= 1 \
                and leaf.shape[0] == n:
            return leaf[srcidx]
        return leaf
    new_state = jax.tree.map(permute, state)
    asas_new = new_state.asas
    # caller-space partner ids (tiled path) move WITH the slots
    p = asas_new.partners
    p = jnp.where(p >= 0, newslot[jnp.clip(p, 0, n - 1)], -1)
    spad = state.asas.partners_s.shape[0] - n_tot
    if spad > 0:
        partners_new = jnp.concatenate(
            [partners_new,
             jnp.full((spad, partners_new.shape[1]), -1, jnp.int32)])
    new_state = new_state.replace(asas=asas_new.replace(
        sort_perm=sort_perm, partners_s=partners_new, partners=p))
    offs = cd_sched.tile_offsets((tR, tC))
    info = dict(counts=counts, occupancy=float(counts.max() / max(C, 1)),
                tile_shape=(tR, tC), offsets=offs,
                budgets=budgets, needs=needs.tolist(),
                gsmax=float(gsmax), nb=nb, nb_local=nb_t, n_tot=n_tot,
                extra_blocks=extra,
                halo_rows=int(sum(budgets)) * block * ndev)
    return new_state, np.asarray(newslot), info


def inscan_tile_refresh(state: SimState, cfg: AsasConfig, tiles,
                        block: int = 256, budgets=()):
    """The tiles-mode refresh as a pure in-scan body: the device side
    of ``refresh_tile_shard`` — 2-D tile sort, caller re-bucketing,
    partner remap, occupancy + corner-halo/budget validation AND the
    full-state slot permutation — with the host's RuntimeError
    escalation replaced by a structured guard word (the tiles analogue
    of ``inscan_spatial_refresh``).

    Returns ``(state', newslot, guard)``: ``guard`` is int32, bit 2 =
    corner-halo/budget contract violation, bit 4 = tile-occupancy
    overflow.  A violating refresh is SKIPPED entirely (old layout
    kept, identity newslot) — staleness is exact, only looser — and
    the host trips the fallback chain (tiles -> spatial -> replicate)
    when the word reaches the edge.
    """
    ac = state.ac
    n = ac.lat.shape[0]
    block = min(block, 256)
    tR, tC = int(tiles[0]), int(tiles[1])
    ndev = tR * tC
    n_tot = state.asas.partners_s.shape[0]
    nb0 = -(-n // block)
    if n_tot % block or n_tot // block <= nb0:
        raise ValueError(
            f"in-scan tile refresh needs partners_s sized to the "
            f"padded layout (got {n_tot} rows for n={n}, block={block}) "
            "— enable tiles mode via Simulation.set_shard first")
    nb = n_tot // block
    extra = nb - nb0
    min_reach = 0.0
    if cfg.reso_on and cfg.reso_method.upper() == "SWARM":
        from ..ops import cr_swarm
        min_reach = float(cr_swarm.R_SWARM)
    newslot, srcidx, sort_perm, partners_new, stats = \
        _tile_shard_refresh(
            ac.lat, ac.lon, ac.gs, ac.alt, ac.vs, ac.active,
            state.asas.sort_perm, state.asas.partners_s,
            block=block, extra=extra, tiles=(tR, tC),
            budgets=tuple(int(b) for b in budgets) if budgets else (),
            tlookahead=float(cfg.dtlookahead), rpz=float(cfg.rpz),
            min_reach_m=min_reach,
            margin_s=float(cfg.sort_every * cfg.dtasas))
    counts, halo_ok, budget_ok, _needs, _gsmax = stats
    overflow = jnp.max(counts) > (n // ndev)
    contract_ok = halo_ok & budget_ok
    guard = (jnp.where(overflow, 4, 0)
             | jnp.where(contract_ok, 0, 2)).astype(jnp.int32)
    ok = contract_ok & ~overflow

    def apply(s):
        def permute(leaf):
            if hasattr(leaf, "ndim") and leaf.ndim >= 1 \
                    and leaf.shape[0] == n:
                return leaf[srcidx]
            return leaf
        s2 = jax.tree.map(permute, s)
        # caller-space partner ids (tiled path) move WITH the slots
        p = s2.asas.partners
        p = jnp.where(p >= 0, newslot[jnp.clip(p, 0, n - 1)], -1)
        return s2.replace(asas=s2.asas.replace(
            sort_perm=sort_perm, partners_s=partners_new, partners=p))

    state2 = jax.lax.cond(ok, apply, lambda s: s, state)
    newslot_out = jnp.where(ok, newslot,
                            jnp.arange(n, dtype=jnp.int32))
    return state2, newslot_out, guard


def inscan_sparse_refresh(state: SimState, cfg: AsasConfig,
                          block: int = 256) -> SimState:
    """The sparse sort refresh as a pure state -> state body, callable
    INSIDE the chunk scan (SimConfig.inscan_refresh): exactly the
    ``refresh_spatial_sort`` sparse branch, minus the host entry.  The
    caller (core/step._refresh_gate) wraps it in the scalar due-cond;
    under trace ``_sparse_sort_refresh`` inlines, so the scan body
    carries the sort as conditional device code instead of a host call
    at every chunk edge."""
    ac = state.ac
    dest, partners_s = _sparse_sort_refresh(
        ac.lat, ac.lon, ac.gs, ac.alt, ac.vs, ac.active,
        state.asas.sort_perm, state.asas.partners_s,
        block=min(block, 256), tlookahead=float(cfg.dtlookahead),
        rpz=float(cfg.rpz))
    return state.replace(asas=state.asas.replace(
        sort_perm=dest, partners_s=partners_s))


def inscan_spatial_refresh(state: SimState, cfg: AsasConfig, ndev: int,
                           block: int = 256, halo_blocks: int = 0):
    """The spatial-mode refresh as a pure in-scan body: the device side
    of ``refresh_spatial_shard`` — stripe sort, caller re-bucketing,
    partner remap, halo/occupancy validation AND the full-state slot
    permutation — with the host's RuntimeError escalation replaced by a
    structured guard word, and the ``newslot`` bijection RETURNED for
    the caller's carry (core/step.RefreshPack composes it across
    in-chunk refreshes; the host applies it to ids/routes once at the
    chunk edge).

    Returns ``(state', newslot, guard)``: ``guard`` is int32, bit 1 =
    stripe-occupancy overflow, bit 2 = halo-coverage violation.  A
    violating refresh is SKIPPED entirely (old layout kept, identity
    newslot) — staleness is exact, only looser — and the host trips the
    fallback-to-replicate path when the word reaches the edge.
    """
    from ..ops import cd_sched
    ac = state.ac
    n = ac.lat.shape[0]
    block = min(block, 256)
    # Layout keyed off the sorted-space partner table like the interval
    # kernel (update_tiled spatial branch): SHARD sizing made it
    # EXACTLY the device-divisible padded size.
    n_tot = state.asas.partners_s.shape[0]
    nb0 = -(-n // block)
    if n_tot % block or n_tot // block <= nb0:
        raise ValueError(
            f"in-scan spatial refresh needs partners_s sized to the "
            f"padded layout (got {n_tot} rows for n={n}, block={block}) "
            "— enable spatial mode via Simulation.set_shard first")
    nb = n_tot // block
    extra = nb - nb0
    nb_l = nb // ndev
    halo_max = (ndev - 1) * nb_l
    halo = halo_max if not halo_blocks else min(int(halo_blocks),
                                               halo_max)
    min_reach = 0.0
    if cfg.reso_on and cfg.reso_method.upper() == "SWARM":
        from ..ops import cr_swarm
        min_reach = float(cr_swarm.R_SWARM)
    newslot, srcidx, sort_perm, partners_new, stats = \
        _spatial_shard_refresh(
            ac.lat, ac.lon, ac.gs, ac.alt, ac.vs, ac.active,
            state.asas.sort_perm, state.asas.partners_s,
            block=block, ndev=int(ndev), extra=extra, halo=halo,
            tlookahead=float(cfg.dtlookahead), rpz=float(cfg.rpz),
            min_reach_m=min_reach,
            margin_s=float(cfg.sort_every * cfg.dtasas))
    counts, halo_ok, _halo_need, _gsmax = stats
    overflow = jnp.max(counts) > (n // ndev)
    guard = (jnp.where(overflow, 1, 0)
             | jnp.where(halo_ok, 0, 2)).astype(jnp.int32)
    ok = halo_ok & ~overflow

    def apply(s):
        def permute(leaf):
            if hasattr(leaf, "ndim") and leaf.ndim >= 1 \
                    and leaf.shape[0] == n:
                return leaf[srcidx]
            return leaf
        s2 = jax.tree.map(permute, s)
        # caller-space partner ids (tiled path) move WITH the slots
        p = s2.asas.partners
        p = jnp.where(p >= 0, newslot[jnp.clip(p, 0, n - 1)], -1)
        return s2.replace(asas=s2.asas.replace(
            sort_perm=sort_perm, partners_s=partners_new, partners=p))

    state2 = jax.lax.cond(ok, apply, lambda s: s, state)
    newslot_out = jnp.where(ok, newslot,
                            jnp.arange(n, dtype=jnp.int32))
    return state2, newslot_out, guard


def spatial_table_size(n, block=256, ndev=1):
    """Rows of the sorted-space partner table in spatial mode (the
    padded layout is device-divisible, so the table is sized to it
    EXACTLY — a per-interval slice of a sharded table would cost an
    O(N*K) reshard every interval)."""
    from ..ops import cd_sched
    return cd_sched.spatial_layout(n, block, ndev)[3]


def update_tiled(state: SimState, cfg: AsasConfig, block: int = 512,
                 impl: str = "lax", mesh=None, mesh_axis: str = "ac",
                 shard_mode: str = "replicate", halo_blocks: int = 0,
                 tile_shape=None,
                 tile_budgets=()) -> Tuple[SimState, RowConflictData]:
    """One ASAS interval via the blockwise large-N backend (ops/cd_tiled.py).

    Same pipeline as ``update`` — detect, resolve, bookkeep, resume
    (reference asas.py:473-504) — but no [N,N] array ever exists: the pair
    space is streamed in tiles and resume-nav hysteresis lives in the [N,K]
    partner table instead of the resopairs matrix.  ``impl`` selects the
    lax.scan formulation ('lax', runs everywhere) or the Pallas TPU kernel
    ('pallas', ops/cd_pallas.py).

    ``mesh`` shards the Pallas kernels' row blocks over a device mesh
    via ``shard_map`` (see ``ops/cd_sched.detect_resolve_sched``); the
    lax backend needs no manual sharding (GSPMD partitions it from the
    state shardings alone).
    """
    ac, asas = state.ac, state.asas
    k = asas.partners.shape[1]
    mvpcfg = cr_mvp.MVPConfig(
        rpz_m=cfg.rpz_m, hpz_m=cfg.hpz_m, tlookahead=cfg.dtlookahead,
        swresohoriz=cfg.swresohoriz, swresospd=cfg.swresospd,
        swresohdg=cfg.swresohdg, swresovert=cfg.swresovert)

    # Cached spatial sort, refreshed by the HOST at chunk boundaries
    # (refresh_spatial_sort below) — never inside the step: an in-jit
    # ``lax.cond``ed refresh was measured to cost the full ~70 ms
    # argsort EVERY interval, because XLA speculatively hoists the pure
    # sort out of the conditional, so the cache never cached.  Any
    # staleness (including the initial identity layout) is exact —
    # block reachability is recomputed from true positions each
    # interval; staleness only loosens the windows.
    perm = asas.sort_perm

    # Resolver mode: the blockwise kernels accumulate per-pair sums for
    # MVP or Eby (additive row reductions — reference MVP.py:149-231,
    # Eby.py:73-138); Swarm adds 7 neighbour sums (all backends); SSD
    # runs the MVP kernels for detection/partner bookkeeping and
    # resolves from the gathered partner table afterwards
    # (cr_ssd.resolve_from_partners — reference asas.py:41-55 keeps CD
    # and CR orthogonal, so any resolver must run at any N).
    reso_m = cfg.reso_method.upper()
    kern_reso = "mvp"
    if cfg.reso_on and reso_m == "EBY":
        kern_reso = "eby"
    elif cfg.reso_on and reso_m == "SWARM":
        kern_reso = "swarm"
    elif cfg.reso_on and reso_m not in ("MVP", "SSD"):
        raise ValueError(
            f"Unknown AsasConfig.reso_method {cfg.reso_method!r}; "
            "expected MVP, EBY, SWARM or SSD.")
    swarm_sums = None
    if impl == "sparse":
        from ..ops import cd_sched
        block = min(block, 256)
        n = ac.lat.shape[0]
        extra_eff = 32
        if shard_mode in ("spatial", "tiles"):
            # Spatial/tiles modes key the padded layout off the
            # sorted-space partner table, which SHARD sizing made
            # EXACTLY the device-divisible padded size (a per-interval
            # slice of a sharded table would reshard O(N*K) every
            # interval).
            n_tot = asas.partners_s.shape[0]
            nb0 = -(-n // block)
            if n_tot % block or n_tot // block <= nb0:
                raise ValueError(
                    f"{shard_mode} mode needs partners_s sized to the "
                    f"padded layout (got {n_tot} rows for n={n}, "
                    f"block={block}) — enable it via "
                    "Simulation.set_shard/SHARD SPATIAL|TILE")
            extra_eff = n_tot // block - nb0
        else:
            n_tot = cd_sched.padded_size(n, block)
        out = cd_sched.detect_resolve_sched(
            ac.lat, ac.lon, ac.trk, ac.gs, ac.alt, ac.vs,
            ac.gseast, ac.gsnorth, ac.active, asas.noreso,
            cfg.rpz, cfg.hpz, cfg.dtlookahead, mvpcfg, block=block,
            k_partners=asas.partners_s.shape[1], perm=perm,
            partners=asas.partners_s[:n_tot],
            resume_rpz_m=cfg.rpz * cfg.resofach,
            tas=ac.tas if kern_reso == "eby" else None,
            cas=ac.cas if kern_reso == "swarm" else None,
            reso=kern_reso, mesh=mesh, mesh_axis=mesh_axis,
            shard_mode=shard_mode, extra_blocks=extra_eff,
            halo_blocks=halo_blocks, tile_shape=tile_shape,
            tile_budgets=tile_budgets)
        if kern_reso == "swarm":
            rd, partners_s, act_new, swarm_sums = out
        else:
            rd, partners_s, act_new = out
    else:
        if impl == "pallas":
            from ..ops import cd_pallas
            detect_fn = functools.partial(cd_pallas.detect_resolve_pallas,
                                          mesh=mesh, mesh_axis=mesh_axis)
        else:
            detect_fn = cd_tiled.detect_resolve_tiled
        extra = None
        if kern_reso == "eby":
            extra = {"tas": ac.tas}
        elif kern_reso == "swarm":
            extra = {"cas": ac.cas}
        out = detect_fn(
            ac.lat, ac.lon, ac.trk, ac.gs, ac.alt, ac.vs,
            ac.gseast, ac.gsnorth, ac.active, asas.noreso,
            cfg.rpz, cfg.hpz, cfg.dtlookahead, mvpcfg, block=block,
            k_partners=k, perm=perm, reso=kern_reso, extra_cols=extra)
        if kern_reso == "swarm":
            rd, swarm_sums = out
        else:
            rd = out

    if cfg.reso_on and kern_reso == "swarm":
        from ..ops import cr_swarm
        # MVP collision-avoidance part from the accumulated MVP sums
        # (the reference runs MVP first, Swarm.py:68), then the blend
        # with the neighbour sums; mvp_active is the PREVIOUS interval's
        # engagement flags, like the dense path (Swarm.py:70-73).
        m_trk, m_gs, m_vs, _m_alt, _e, _n = cr_mvp.resolve_from_sums(
            rd.sum_dve, rd.sum_dvn, rd.sum_dvv, rd.tsolv,
            ac.alt, ac.gseast, ac.gsnorth, ac.vs, ac.trk, ac.gs,
            ac.selalt, state.ap.vs, asas.alt,
            cfg.vmin, cfg.vmax, cfg.vsmin, cfg.vsmax, mvpcfg,
            resooff=asas.resooff)
        _, selcas, _ = aero.vcasormach(ac.selspd, ac.alt)
        newtrk, newgs, newvs, newalt = cr_swarm.resolve_from_sums(
            *swarm_sums, ac.alt, ac.trk, ac.cas, ac.vs,
            ac.gseast, ac.gsnorth, ac.active,
            m_trk, m_gs, m_vs, asas.active,
            state.ap.trk, selcas, ac.selvs, cfg.vmin, cfg.vmax)
        asase = newgs * jnp.sin(jnp.radians(newtrk))
        asasn = newgs * jnp.cos(jnp.radians(newtrk))
        # the whole swarm updates once any conflict exists (Swarm
        # semantics, see core/asas.update)
        upd = ac.active & (rd.nconf > 0)
        asas = asas.replace(
            trk=jnp.where(upd, newtrk, asas.trk),
            tas=jnp.where(upd, newgs, asas.tas),
            vs=jnp.where(upd, newvs, asas.vs),
            alt=jnp.where(upd, newalt, asas.alt),
            asase=jnp.where(upd, asase, asas.asase),
            asasn=jnp.where(upd, asasn, asas.asasn))
    elif cfg.reso_on and reso_m == "EBY":
        from ..ops import cr_eby
        newtrk, newgs, newvs, newalt = cr_eby.resolve_from_sums(
            rd.sum_dve, rd.sum_dvn, rd.sum_dvv,
            ac.alt, ac.vs, ac.trk, ac.tas, cfg.vmin, cfg.vmax)
        asase = newgs * jnp.sin(jnp.radians(newtrk))
        asasn = newgs * jnp.cos(jnp.radians(newtrk))
        upd = rd.inconf
        asas = asas.replace(
            trk=jnp.where(upd, newtrk, asas.trk),
            tas=jnp.where(upd, newgs, asas.tas),
            vs=jnp.where(upd, newvs, asas.vs),
            alt=jnp.where(upd, newalt, asas.alt),
            asase=jnp.where(upd, asase, asas.asase),
            asasn=jnp.where(upd, asasn, asas.asasn))
    elif cfg.reso_on and reso_m == "MVP":
        newtrk, newgs, newvs, newalt, asase, asasn = cr_mvp.resolve_from_sums(
            rd.sum_dve, rd.sum_dvn, rd.sum_dvv, rd.tsolv,
            ac.alt, ac.gseast, ac.gsnorth, ac.vs, ac.trk, ac.gs,
            ac.selalt, state.ap.vs, asas.alt,
            cfg.vmin, cfg.vmax, cfg.vsmin, cfg.vsmax, mvpcfg,
            resooff=asas.resooff)
        upd = rd.inconf
        asas = asas.replace(
            trk=jnp.where(upd, newtrk, asas.trk),
            tas=jnp.where(upd, newgs, asas.tas),
            vs=jnp.where(upd, newvs, asas.vs),
            alt=jnp.where(upd, newalt, asas.alt),
            asase=jnp.where(upd, asase, asas.asase),
            asasn=jnp.where(upd, asasn, asas.asasn))

    def ssd_resolve(cur_asas, ptable):
        """SSD from the [N, P] partner table (cr_ssd.resolve_from_partners
        docstring records the K-truncation semantics).  Horizontal-only,
        like the dense path (SSD.py:99-104)."""
        from ..ops import cr_ssd
        rs = cfg.priocode.upper() if cfg.swprio \
            and cfg.priocode.upper().startswith("RS") else "RS1"
        ssdcfg = cr_ssd.SSDConfig(rpz_m=cfg.rpz_m,
                                  tlookahead=cfg.dtlookahead, priocode=rs)
        newtrk, newgs = cr_ssd.resolve_from_partners(
            ptable, rd.inconf, ac.lat, ac.lon, ac.alt, ac.trk, ac.gs,
            ac.vs, ac.gseast, ac.gsnorth, ac.active,
            cfg.vmin, cfg.vmax, ssdcfg, hdg=ac.hdg,
            ap_trk=state.ap.trk, ap_tas=state.ap.tas)
        upd = rd.inconf
        return cur_asas.replace(
            trk=jnp.where(upd, newtrk, cur_asas.trk),
            tas=jnp.where(upd, newgs, cur_asas.tas),
            asase=jnp.where(upd, newgs * jnp.sin(jnp.radians(newtrk)),
                            cur_asas.asase),
            asasn=jnp.where(upd, newgs * jnp.cos(jnp.radians(newtrk)),
                            cur_asas.asasn))

    if impl == "sparse":
        if cfg.reso_on and reso_m == "SSD":
            # The in-kernel-merged table is SORTED-space; translate to
            # caller slots for the gathered VO construction (one scatter
            # + two [N, K] gathers per interval).
            n = ac.lat.shape[0]
            ptable = cd_sched.partners_to_caller(
                perm, partners_s, n, n_tot)
            asas = ssd_resolve(asas, ptable)
        if cfg.reso_on and kern_reso == "swarm":
            # Whole swarm follows ASAS once any conflict triggered a
            # resolve (asas.py:487 gate + Swarm.py:101-102)
            act_new = jnp.where(rd.nconf > 0, ac.active, act_new)
        # Resume-nav already happened IN-KERNEL (keep + merge on the
        # sorted-space table) — just store the new table + flags.
        spad = asas.partners_s.shape[0] - partners_s.shape[0]
        if spad > 0:
            partners_s = jnp.concatenate(
                [partners_s,
                 jnp.full((spad, partners_s.shape[1]), -1, jnp.int32)])
        asas = asas.replace(
            partners_s=partners_s,
            active=act_new & cfg.reso_on,
            inconf=rd.inconf,
            tcpamax=rd.tcpamax.astype(asas.tcpamax.dtype),
            nconf_cur=rd.nconf,
            nlos_cur=rd.nlos)
        return state.replace(asas=asas), rd

    # Resume-nav on the partner table, matching the dense path's pruning of
    # (old | new swconfl) through resume_nav (asas.py:409-471) as closely as
    # the K-wide table allows: prune the old partners first (so stale
    # past-CPA entries cannot evict still-engaged ones from the K slots),
    # merge in this interval's fresh conflicts, then prune the merged table
    # (so a borderline fresh conflict already past CPA releases immediately
    # instead of staying engaged one interval longer than the dense path).
    prune = lambda tbl: cd_tiled.partner_keep(
        tbl, ac.lat, ac.lon, ac.gseast, ac.gsnorth, ac.trk,
        ac.active, cfg.rpz, cfg.rpz * cfg.resofach)
    new_idx = cd_tiled.topk_partners(rd, k)
    merged = cd_tiled.merge_partners(new_idx, asas.partners,
                                     prune(asas.partners))
    partners = jnp.where(prune(merged), merged, -1)

    if cfg.reso_on and reso_m == "SSD":
        # SSD resolves from the freshly merged table (fresh top-K
        # conflicts first + still-engaged partners — caller space here)
        asas = ssd_resolve(asas, partners)

    act_tbl = jnp.any(partners >= 0, axis=1)
    if cfg.reso_on and kern_reso == "swarm":
        # Whole swarm follows ASAS once any conflict triggered a resolve
        # (asas.py:487 gate + Swarm.py:101-102 active.fill(True))
        act_tbl = jnp.where(rd.nconf > 0, ac.active, act_tbl)
    asas = asas.replace(
        partners=partners,
        active=act_tbl & cfg.reso_on,
        inconf=rd.inconf,
        tcpamax=rd.tcpamax.astype(asas.tcpamax.dtype),
        nconf_cur=rd.nconf,
        nlos_cur=rd.nlos)
    return state.replace(asas=asas), rd
