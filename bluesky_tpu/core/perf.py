"""Vectorized OpenAP-style aircraft performance model (jitted).

Parity with the reference's default performance model
(``bluesky/traffic/performance/openap/``): flight-phase inference from
speed/vertical-rate/altitude (phase.py:32-64), phase-dependent drag polar
(perfoap.py:133-149), a bypass-ratio thrust-ratio model (thrust.py:5-130),
quadratic fuel flow in thrust ratio (perfoap.py:162-164), and a
phase-dependent flight envelope applied to pilot intents
(perfoap.py:185-209).

TPU-first: the reference rebuilds an [N,6] limit matrix per step with a
Python loop over unique type strings (perfoap.py:212-265).  Here every
envelope quantity is a per-aircraft column filled once at creation, and phase
selection is a handful of fused ``jnp.where`` lattices — no strings, no
loops, no host sync.
"""
import jax.numpy as jnp

from ..ops import aero
from ..models.perf_coeffs import (
    PH_NA, PH_TO, PH_IC, PH_CL, PH_CR, PH_DE, PH_AP, PH_LD, PH_GD)


def infer_phase(tas, vs, alt):
    """Fixed-wing flight phase from state (reference phase.py:32-64).

    Thresholds are in knots/fpm/ft in the reference; converted here.
    Later assignments override earlier ones, so the where-chain is applied in
    the same order.
    """
    spd_kt = tas / aero.kts
    roc_fpm = vs / (0.00508)   # reference divides SI roc by 0.00508 (fpm)
    alt_ft = alt / aero.ft

    ph = jnp.zeros(tas.shape, dtype=jnp.int32)
    ph = jnp.where((alt_ft <= 10) & (roc_fpm <= 100) & (roc_fpm >= -100), PH_GD, ph)
    ph = jnp.where((alt_ft >= 0) & (alt_ft <= 1000) & (roc_fpm >= 0), PH_IC, ph)
    ph = jnp.where((alt_ft >= 0) & (alt_ft <= 1000) & (roc_fpm <= 0), PH_AP, ph)
    ph = jnp.where((alt_ft >= 1000) & (roc_fpm >= 100), PH_CL, ph)
    ph = jnp.where((alt_ft >= 1000) & (roc_fpm <= -100), PH_DE, ph)
    ph = jnp.where((alt_ft >= 5000) & (roc_fpm <= 100) & (roc_fpm >= -100), PH_CR, ph)
    del spd_kt
    return ph


def _thrust_ratio_takeoff(bpr, tas, alt):
    """Takeoff thrust-ratio model (reference thrust.py:43-58)."""
    g0c = 0.0606 * bpr + 0.6337
    mach = aero.vtas2mach(tas, alt)
    pp = aero.vpressure(alt) / aero.p0
    a = -0.4327 * pp ** 2 + 1.3855 * pp + 0.0472
    z = 0.9106 * pp ** 3 - 1.7736 * pp ** 2 + 1.8697 * pp
    x = 0.1377 * pp ** 3 - 0.4374 * pp ** 2 + 1.3003 * pp
    return (a - 0.377 * (1 + bpr) / jnp.sqrt((1 + 0.82 * bpr) * g0c) * z * mach
            + (0.23 + 0.19 * jnp.sqrt(bpr)) * x * mach ** 2)


def _thrust_ratio_inflight(tas, alt, vs, thr0):
    """In-flight thrust-ratio model (reference thrust.py:61-130)."""
    roc = jnp.abs(vs / aero.fpm)
    v = jnp.maximum(tas, 10.0)

    mach = aero.vtas2mach(v, alt)
    vcas = aero.vtas2cas(v, alt)

    p = aero.vpressure(alt)
    p10 = aero.vpressure(10000 * aero.ft)
    p35 = aero.vpressure(35000 * aero.ft)

    f35 = (200 + 0.2 * thr0 / 4.448) * 4.448
    mach_ref = 0.8
    vcas_ref = aero.vmach2cas(jnp.asarray(mach_ref), 35000 * aero.ft)

    mratio = mach / mach_ref
    d = jnp.where(
        mratio < 0.85, 0.73, jnp.where(
            mratio < 0.92, 0.73 + (0.69 - 0.73) / (0.92 - 0.85) * (mratio - 0.85),
            jnp.where(
                mratio < 1.08, 0.66 + (0.63 - 0.66) / (1.08 - 1.00) * (mratio - 1.00),
                jnp.where(
                    mratio < 1.15, 0.63 + (0.60 - 0.63) / (1.15 - 1.08) * (mratio - 1.08),
                    0.60))))
    b = mratio ** (-0.11)
    ratio_seg3 = d * jnp.log(p / p35) + b

    vratio = vcas / vcas_ref
    a = vratio ** (-0.1)
    n = jnp.where(roc < 1500, 0.89, jnp.where(roc < 2500, 0.93, 0.97))
    ratio_seg2 = a * (p / p35) ** (-0.355 * vratio + n)

    f10 = f35 * a * (p10 / p35) ** (-0.355 * vratio + n)
    m = jnp.where(vratio < 0.67, 0.4,
                  jnp.where(vratio < 0.75, 0.39,
                            jnp.where(vratio < 0.83, 0.38,
                                      jnp.where(vratio < 0.92, 0.37, 0.36))))
    m = jnp.where(roc < 1500, m - 0.06, jnp.where(roc < 2500, m - 0.01, m))
    ratio_seg1 = m * (p / p35) + (f10 / f35 - m * (p10 / p35))

    ratio = jnp.where(alt > 35000 * aero.ft, ratio_seg3,
                      jnp.where(alt > 10000 * aero.ft, ratio_seg2, ratio_seg1))
    return ratio * f35 / thr0


def update(perf, tas, vs, alt):
    """Per-step performance update: phase, envelope, drag, thrust, fuel flow.

    Functional replacement of ``OpenAP.update`` (perfoap.py:115-183);
    returns a new PerfArrays plus the per-aircraft bank angle [rad].
    """
    phase = infer_phase(tas, vs, alt)

    # Phase-dependent envelope selection (replaces perfoap.py:212-265).
    er = (phase == PH_CL) | (phase == PH_CR) | (phase == PH_DE)
    vmin = jnp.zeros_like(tas)
    vmin = jnp.where(phase == PH_TO, perf.vminto, vmin)
    vmin = jnp.where(phase == PH_IC, perf.vminic, vmin)
    vmin = jnp.where(er, perf.vminer, vmin)
    vmin = jnp.where(phase == PH_AP, perf.vminap, vmin)
    vmin = jnp.where(phase == PH_LD, perf.vminld, vmin)

    vmax = jnp.where(phase == PH_TO, perf.vmaxto, perf.vmaxer)
    vmax = jnp.where(phase == PH_IC, perf.vmaxic, vmax)
    vmax = jnp.where(phase == PH_AP, perf.vmaxap, vmax)
    vmax = jnp.where(phase == PH_LD, perf.vmaxld, vmax)

    # Phase-dependent zero-lift drag coefficient (perfoap.py:133-143)
    cd0 = perf.cd0_clean
    cd0 = jnp.where(phase == PH_TO, perf.cd0_to, cd0)
    cd0 = jnp.where(phase == PH_IC, perf.cd0_ic, cd0)
    cd0 = jnp.where(phase == PH_AP, perf.cd0_ap, cd0)
    cd0 = jnp.where(phase == PH_LD, perf.cd0_ld, cd0)
    cd0 = jnp.where(phase == PH_GD, perf.cd0_gd, cd0)

    rho = aero.vdensity(alt)
    safe_tas = jnp.maximum(tas, 1.0)
    rhovs = 0.5 * rho * safe_tas * safe_tas * perf.sref
    cl = perf.mass * aero.g0 / rhovs
    drag = rhovs * (cd0 + perf.k * cl * cl)

    # Thrust ratio by phase (thrust.py:21-39): takeoff model at TO, inflight
    # at IC/CL/CR, 15% of inflight at DE, zero at LD/GD.
    thr0 = perf.engnum * perf.engthrust
    tr_to = _thrust_ratio_takeoff(perf.engbpr, tas, alt)
    tr_if = _thrust_ratio_inflight(tas, alt, vs, thr0)
    tr = jnp.zeros_like(tas)
    tr = jnp.where(phase == PH_TO, tr_to, tr)
    tr = jnp.where((phase == PH_IC) | (phase == PH_CL) | (phase == PH_CR), tr_if, tr)
    tr = jnp.where(phase == PH_DE, 0.15 * tr_if, tr)
    thrust = thr0 * tr

    fuelflow = perf.engnum * (perf.ff_a * tr * tr + perf.ff_b * tr + perf.ff_c)

    # Bank angle by phase (perfoap.py:172-173), in radians for kinematics
    bank_deg = jnp.full_like(tas, 25.0)
    bank_deg = jnp.where((phase == PH_TO) | (phase == PH_LD), 15.0, bank_deg)
    bank_deg = jnp.where((phase == PH_IC) | (phase == PH_CR) | (phase == PH_AP),
                         35.0, bank_deg)
    bank = jnp.radians(bank_deg)

    new_perf = perf.replace(phase=phase, vmin=vmin, vmax=vmax,
                            thrust=thrust, drag=drag, fuelflow=fuelflow)
    return new_perf, bank


def limits(perf, intent_tas, intent_vs, intent_alt, ax, smooth=None):
    """Clip pilot intents to the flight envelope (perfoap.py:185-209).

    ``smooth`` (diff.smooth.SmoothConfig, differentiable mode only)
    swaps the hard CAS clamp for its straight-through estimator: the
    forward envelope is enforced bit-exactly, but the backward pass
    treats the clip as identity so gradients keep flowing when an
    intent is pinned against a limit (the documented perf-clamp STE,
    docs/PERF_ANALYSIS.md §differentiable).  The vs selections are
    ``jnp.where`` lattices — already differentiable in both branches.
    """
    allow_alt = jnp.minimum(intent_alt, perf.hmax)

    intent_cas = aero.vtas2cas(intent_tas, allow_alt)
    if smooth is not None and smooth.ste_caps:
        from ..diff.smooth import ste_clip
        allow_cas = ste_clip(intent_cas, perf.vmin, perf.vmax)
    else:
        allow_cas = jnp.clip(intent_cas, perf.vmin, perf.vmax)
    allow_tas = aero.vcas2tas(allow_cas, allow_alt)

    vs_max_with_acc = (1.0 - ax / perf.axmax) * perf.vsmax
    allow_vs = jnp.where(intent_vs > perf.vsmax, vs_max_with_acc, intent_vs)
    allow_vs = jnp.where(intent_vs < perf.vsmin, perf.vsmin, allow_vs)
    return allow_tas, allow_vs, allow_alt


def acceleration(phase):
    """Fixed phase-dependent acceleration magnitude (perfoap.py:271-280)."""
    return jnp.where(phase == PH_GD, 2.0, 0.5)
