"""Simulation core: state pytree, traffic facade, physics, step function."""
