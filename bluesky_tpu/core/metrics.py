"""Research traffic metrics: cell occupancy (CoCa) + conflict-geometry
complexity (HB two-circle method).

Capability parity with the reference ``traffic/metric.py`` (1.4k LoC of
research NumPy + matplotlib): the same measured quantities — per-cell
aircraft counts over the reference's 18x18x12 sector grid
(metric_Area:53-158 / metric_CoCa:160-505) and the Hoekstra-Bussink
conflict-geometry complexity inside a FIR circle (metric_HB:508-1300) —
restructured TPU-first:

* Cell occupancy is one ``digitize``-style binning over the padded
  aircraft arrays instead of per-aircraft Python loops.
* The HB complexity counts pairwise CPA encounters (t_cpa within the
  1800 s lookahead, CPA distance < 5 nm, altitude difference < 1000 ft)
  from the same broadcast geometry the CD kernel uses.
* Results log to a METLOG CSV via the datalog EventLogger instead of
  matplotlib figures; sampling happens at chunk edges on the host copy.
"""
import numpy as np

from ..ops import aero

NM = aero.nm
FT = aero.ft


class MetricsArea:
    """The reference metric sector grid (metric.py:53-66 defaults):
    ncells x ncells columns of `distance` nm, nlevels flight levels."""

    def __init__(self, lat=55.5, lon=1.7, ncells=18, nlevels=12,
                 cell_nm=20.0, fl_low=8500.0, fl_high=41500.0):
        self.lat0 = lat
        self.lon0 = lon
        self.ncells = ncells
        self.nlevels = nlevels
        self.cell_nm = cell_nm
        self.fl_low = fl_low
        self.fl_high = fl_high
        # Grid spans south/east from the anchor (bearingS/bearingE)
        self.dlat = -cell_nm / 60.0
        self.dlon = cell_nm / 60.0 / max(
            0.2, np.cos(np.radians(lat)))

    def cell_indices(self, lat, lon, alt):
        """[N] -> (i, j, k) cell indices; -1 outside the grid."""
        i = np.floor((lat - self.lat0) / self.dlat).astype(int)
        j = np.floor((lon - self.lon0) / self.dlon).astype(int)
        alt_ft = alt / FT
        k = np.floor((alt_ft - self.fl_low)
                     / ((self.fl_high - self.fl_low) / self.nlevels)
                     ).astype(int)
        inside = ((i >= 0) & (i < self.ncells) & (j >= 0)
                  & (j < self.ncells) & (k >= 0) & (k < self.nlevels))
        return np.where(inside, i, -1), np.where(inside, j, -1), \
            np.where(inside, k, -1), inside

    def cell_area_nm2(self):
        """Horizontal cell area [nm^2] (metric_Area.cellArea:99-107 —
        the reference derives it from the region corner points; the
        regular grid makes it the cell square)."""
        return self.cell_nm * self.cell_nm

    def cell_centroid(self, i, j):
        """(lat, lon) centre of column cell (i, j)
        (metric_Area.centroid_of_polygon:124-145 on a regular grid)."""
        return (self.lat0 + (i + 0.5) * self.dlat,
                self.lon0 + (j + 0.5) * self.dlon)


def coca_counts(area, lat, lon, alt, active):
    """Cell-occupancy histogram [ncells, ncells, nlevels] + summary
    (metric_CoCa.applyMetric:346-505, vectorized)."""
    i, j, k, inside = area.cell_indices(lat, lon, alt)
    sel = inside & active
    counts = np.zeros((area.ncells, area.ncells, area.nlevels), int)
    np.add.at(counts, (i[sel], j[sel], k[sel]), 1)
    return counts


def coca_cell_stats(dwell, hdg, spd_kts, vspd_fpm, window):
    """The reference's per-cell CoCa interaction statistics
    (metric_CoCa.applyMetric:346-447), for ONE cell's occupants.

    Inputs are the occupants' dwell times [s] within the reset window,
    headings [deg], speeds [kts] and vertical speeds [fpm]; ``window``
    is the reset window length (metric.py:186 resettime).  Returns the
    reference's 6 columns: [combined, occupancy, ac-, spd-, hdg-,
    vspd-interactions], with the combined metric
    c1 * (c2 + c3 + c4) of the normalized interaction terms
    (metric.py:442-447).  The peculiar shrinking-list accumulation is
    kept verbatim — it is the published quantity.
    """
    order = np.argsort(dwell)
    times = list(np.asarray(dwell, float)[order])
    headings = list(np.asarray(hdg, float)[order])
    speeds = list(np.asarray(spd_kts, float)[order])
    vspeeds = list(np.asarray(vspd_fpm, float)[order])
    actimes = list(times)
    # vertical-speed tri-state (metric.py:375-381)
    vspeeds = [0 if -500.0 <= v <= 500.0 else (1 if v > 500.0 else -1)
               for v in vspeeds]

    occupancy = sum(times) / window
    if len(times) < 2:
        return [0.0, occupancy, 0.0, 0.0, 0.0, 0.0]

    acint, spdint, hdgint, vspdint = [], [], [], []
    for _k in range(len(times)):
        aircraft = len(times)
        time_n = times[0] / window
        actime_n = actimes[0] / window
        acint.append(aircraft * (aircraft - 1) * actime_n ** aircraft)

        c = sum(1 for u in range(1, len(speeds))
                if abs(speeds[0] - speeds[u]) > 35.0)
        spdint.append(2 * c * time_n ** (c + 1))
        c = sum(1 for u in range(1, len(headings))
                if abs(headings[0] - headings[u]) > 20.0)
        hdgint.append(2 * c * time_n ** (c + 1))
        c = sum(1 for u in range(1, len(vspeeds))
                if vspeeds[0] != vspeeds[u])
        vspdint.append(2 * c * time_n ** (c + 1))

        for x in range(1, len(actimes)):
            actimes[x] = actimes[x] - actimes[0]
        del actimes[0], times[0], vspeeds[0], speeds[0], headings[0]

    pre = [sum(acint), sum(spdint), sum(hdgint), sum(vspdint)]
    occ = occupancy if occupancy > 0 else 1.0
    c1, c2, c3, c4 = (v / occ for v in pre)
    return [c1 * (c2 + c3 + c4), occupancy, c1, c2, c3, c4]


def hb_complexity(lat, lon, alt, tas, trk, active,
                  ctrlat, ctrlon, radius_nm,
                  dist_range_nm=5.0, alt_range_ft=1000.0,
                  time_lookahead=1800.0):
    """Two-circle conflict-geometry complexity (metric_HB:580-1300).

    Counts encounter pairs inside the FIR circle whose CPA lies within
    ``dist_range_nm`` / ``alt_range_ft`` inside the lookahead, and the
    per-aircraft share involved.  Returns (complexity, n_selected,
    compl_ac, sel, per_ac) where ``per_ac`` is each selected aircraft's
    encounter count — the per-aircraft complexity column of the
    reference's Metric-HB CSV rows (metric.py saveData:1004-1023).
    """
    from ..ops.geo import kwikdist_wrapped
    d_fir = kwikdist_wrapped(ctrlat, ctrlon, lat, lon, xp=np)
    sel = active & (np.asarray(d_fir) < radius_nm)
    n = int(sel.sum())
    if n < 2:
        return 0, n, 0, sel, np.zeros(n, int)
    lat, lon = lat[sel], lon[sel]
    alt, tas, trk = alt[sel], tas[sel], trk[sel]

    # Flat-earth relative geometry (the HB method works in nm around
    # the FIR anchor, metric.py:595-612)
    coslat = np.cos(np.radians(ctrlat))
    x = (lon - ctrlon) * 60.0 * coslat          # [nm]
    y = (lat - ctrlat) * 60.0
    vx = tas / NM * np.sin(np.radians(trk))     # [nm/s]
    vy = tas / NM * np.cos(np.radians(trk))

    dx = x[None, :] - x[:, None]
    dy = y[None, :] - y[:, None]
    dvx = vx[None, :] - vx[:, None]
    dvy = vy[None, :] - vy[:, None]
    dv2 = dvx * dvx + dvy * dvy
    dv2 = np.where(dv2 < 1e-12, 1e-12, dv2)
    tcpa = -(dvx * dx + dvy * dy) / dv2
    dcpa2 = (dx + dvx * tcpa) ** 2 + (dy + dvy * tcpa) ** 2
    dalt = np.abs(alt[None, :] - alt[:, None]) / FT

    enc = ((tcpa > 0.0) & (tcpa < time_lookahead)
           & (dcpa2 < dist_range_nm ** 2) & (dalt < alt_range_ft))
    np.fill_diagonal(enc, False)
    complexity = int(enc.sum()) // 2            # unique pairs
    compl_ac = int(enc.any(axis=1).sum())
    return complexity, n, compl_ac, sel, enc.sum(axis=1)


class Metrics:
    """Coordinator (reference Metric:1311-1443): periodic evaluation of
    the selected metric, CSV logging, METRICS stack command."""

    NAMES = ("CoCa-Metric", "HB-Metric")

    def __init__(self, sim):
        self.sim = sim
        self.metric_number = -1      # -1 = off
        self.dt = 1.0
        self.tnext = 0.0
        self.area = MetricsArea()
        self.fir_circle_point = (52.6, 5.4)
        self.fir_circle_radius = 230.0     # [nm]
        self.coca_window = 5.0       # [s] reset window (metric.py:186)
        # per-slot (cell_key, entry simt) for the CoCa dwell times
        self._cell_entry = {}
        # latest scalar outputs, exposed to PLOT (plotter parent
        # 'metrics': e.g. "PLOT simt metrics.complexity")
        self.complexity = 0
        self.n_selected = 0
        self.compl_ac = 0
        self.coca_total = 0
        self.coca_max = 0
        self.coca_combined = 0.0
        # per-sim registry: W multi-world sims keep separate METLOGs
        self.logger = sim.datalog.define_event(
            "METLOG",
            "Metrics log: metric name, then metric-specific columns "
            "(CoCa cell rows: cell-id, n, centroid-lat/lon, combined, "
            "occupancy, ac-, spd-, hdg-, vspd-interactions, "
            "metric.py:346-447 + 99-145; HB "
            "aircraft rows: acid, lat, lon, alt_ft, spd_kts, trk, "
            "ntraf, compl, metric.py:1004-1023)")
        sim.plotter.register_data_parent(self, "metrics")

    # ------------------------------------------------------------ command
    def toggle(self, flag=None, dt=None):
        """METRICS OFF / METRICS n [dt] (Metric.toggle:1358-1387)."""
        if flag is None:
            state = "OFF" if self.metric_number < 0 \
                else self.NAMES[self.metric_number]
            return True, f"METRICS {state} (dt={self.dt})"
        if isinstance(flag, str) and flag.upper() in ("OFF", "0"):
            self.metric_number = -1
            self.logger.stop()       # flush + close our METLOG file
            return True, "Metrics OFF"
        try:
            num = int(float(flag))
        except (TypeError, ValueError):
            return False, "METRICS OFF or METRICS 1/2 [dt]"
        if num <= 0:
            self.metric_number = -1
            self.logger.stop()
            return True, "Metrics OFF"
        if num > len(self.NAMES):
            return False, "No such metric"
        if dt is not None:
            self.dt = float(dt)
        self.metric_number = num - 1
        self.tnext = self.sim.simt
        # (Re)open OUR file on every activation: the METLOG logger is
        # process-global (datalog registry), so "already active" may be
        # a different Simulation's leftover file — rotating guarantees
        # this sim's rows land in a file under the current log_path.
        # (Two sims logging METRICS concurrently in one process share
        # the registry entry and the later activation wins the file —
        # the reference's global datalog has the same property.)
        self.logger.stop()
        self.logger.start(self.sim)
        return True, (f"Activated {self.NAMES[self.metric_number]} "
                      f"({num}), dt={self.dt:.2f}")

    # ------------------------------------------------------------- update
    def update(self, edge=None):
        """Evaluate the active metric when due (chunk edges).

        ``edge`` is a retired ``ChunkEdge`` (simulation/pipeline.py):
        the pipelined loop passes it so every field below comes out of
        the fused telemetry pack — ONE device->host copy per edge
        instead of a dozen ``np.asarray`` pulls — and the sampling
        timestamp is the edge's own clock, not a blocking device read.
        Without it (synchronous edges) the live state is sampled as
        before."""
        if self.metric_number < 0:
            return
        t = edge.simt if edge is not None else self.sim.simt
        if t < self.tnext - 1e-9:
            return
        self.tnext = t + self.dt
        st = edge.fetch() if edge is not None else self.sim.traf.state.ac
        active = np.asarray(st.active)
        lat = np.asarray(st.lat)
        lon = np.asarray(st.lon)
        alt = np.asarray(st.alt)
        if self.metric_number == 0:
            counts = coca_counts(self.area, lat, lon, alt, active)
            self.last_counts = counts
            self.coca_total = int(counts.sum())
            self.coca_max = int(counts.max())
            # ---- per-cell statistics (metric_CoCa.applyMetric) ----
            i, j, k, inside = self.area.cell_indices(lat, lon, alt)
            trk = np.asarray(st.trk)
            cas = np.asarray(st.cas) / aero.kts
            vs = np.asarray(st.vs) / aero.fpm
            keys = (i * self.area.ncells + j) * self.area.nlevels + k
            occupants = {}
            idxs = np.flatnonzero(active & inside)
            for slot in idxs:
                key = int(keys[slot])
                # entries are validated by CALLSIGN: a reused slot must
                # not inherit the deleted occupant's cell-entry time
                acid = self.sim.traf.ids[slot]
                prev = self._cell_entry.get(slot)
                if prev is None or prev[0] != key or prev[2] != acid:
                    self._cell_entry[slot] = (key, t, acid)
                occupants.setdefault(key, []).append(slot)
            # drop stale entries (deleted aircraft / left the grid)
            live = set(int(s_) for s_ in idxs)
            self._cell_entry = {s_: v for s_, v in
                                self._cell_entry.items() if s_ in live}
            combined_sum = 0.0
            for key, slots in sorted(occupants.items()):
                dwell = [min(t - self._cell_entry[s_][1]
                             + self.dt, self.coca_window)
                         for s_ in slots]
                row = coca_cell_stats(dwell, trk[slots], cas[slots],
                                      vs[slots], self.coca_window)
                combined_sum += row[0]
                ci = key // (self.area.ncells * self.area.nlevels)
                cj = (key // self.area.nlevels) % self.area.ncells
                clat, clon = self.area.cell_centroid(ci, cj)
                self.logger.log(self.sim, ["CoCa"], [key], [len(slots)],
                                [round(clat, 4)], [round(clon, 4)],
                                *[[round(v, 6)] for v in row], simt=t)
            self.coca_combined = combined_sum
            self.last_coca_cells = occupants
        else:
            tas = np.asarray(st.tas)
            trk = np.asarray(st.trk)
            cx, n, cac, sel, per_ac = hb_complexity(
                lat, lon, alt, tas, trk, active,
                self.fir_circle_point[0], self.fir_circle_point[1],
                self.fir_circle_radius)
            self.last_hb = (cx, n, cac)
            self.complexity = cx
            self.n_selected = n
            self.compl_ac = cac
            # per-aircraft rows like the reference Metric-HB CSV
            # (metric.py saveData:1004-1023): acid, lat, lon, alt[ft],
            # spd[kts], trk, ntraf, compl
            idx = np.flatnonzero(sel)
            if len(idx):
                ids = [self.sim.traf.ids[s_] or f"#{s_}" for s_ in idx]
                self.logger.log(
                    self.sim, ["HB"] * len(idx), ids,
                    np.round(lat[idx], 5), np.round(lon[idx], 5),
                    np.round(alt[idx] / FT, 1),
                    np.round(tas[idx] / aero.kts, 1),
                    np.round(trk[idx], 1),
                    [n] * len(idx), per_ac, simt=t)
            else:
                # schema-stable empty row (same 8 columns as aircraft
                # rows, acid '-')
                self.logger.log(self.sim, ["HB"], ["-"], [0.0], [0.0],
                                [0.0], [0.0], [0.0], [n], [0], simt=t)

    def reset(self):
        self.metric_number = -1
        self.tnext = 0.0
        self._cell_entry = {}
        # PLOT-exposed scalars must not leak across scenarios
        self.complexity = 0
        self.n_selected = 0
        self.compl_ac = 0
        self.coca_total = 0
        self.coca_max = 0
        self.coca_combined = 0.0
