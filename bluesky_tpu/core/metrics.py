"""Research traffic metrics: cell occupancy (CoCa) + conflict-geometry
complexity (HB two-circle method).

Capability parity with the reference ``traffic/metric.py`` (1.4k LoC of
research NumPy + matplotlib): the same measured quantities — per-cell
aircraft counts over the reference's 18x18x12 sector grid
(metric_Area:53-158 / metric_CoCa:160-505) and the Hoekstra-Bussink
conflict-geometry complexity inside a FIR circle (metric_HB:508-1300) —
restructured TPU-first:

* Cell occupancy is one ``digitize``-style binning over the padded
  aircraft arrays instead of per-aircraft Python loops.
* The HB complexity counts pairwise CPA encounters (t_cpa within the
  1800 s lookahead, CPA distance < 5 nm, altitude difference < 1000 ft)
  from the same broadcast geometry the CD kernel uses.
* Results log to a METLOG CSV via the datalog EventLogger instead of
  matplotlib figures; sampling happens at chunk edges on the host copy.
"""
import numpy as np

from ..ops import aero

NM = aero.nm
FT = aero.ft


class MetricsArea:
    """The reference metric sector grid (metric.py:53-66 defaults):
    ncells x ncells columns of `distance` nm, nlevels flight levels."""

    def __init__(self, lat=55.5, lon=1.7, ncells=18, nlevels=12,
                 cell_nm=20.0, fl_low=8500.0, fl_high=41500.0):
        self.lat0 = lat
        self.lon0 = lon
        self.ncells = ncells
        self.nlevels = nlevels
        self.cell_nm = cell_nm
        self.fl_low = fl_low
        self.fl_high = fl_high
        # Grid spans south/east from the anchor (bearingS/bearingE)
        self.dlat = -cell_nm / 60.0
        self.dlon = cell_nm / 60.0 / max(
            0.2, np.cos(np.radians(lat)))

    def cell_indices(self, lat, lon, alt):
        """[N] -> (i, j, k) cell indices; -1 outside the grid."""
        i = np.floor((lat - self.lat0) / self.dlat).astype(int)
        j = np.floor((lon - self.lon0) / self.dlon).astype(int)
        alt_ft = alt / FT
        k = np.floor((alt_ft - self.fl_low)
                     / ((self.fl_high - self.fl_low) / self.nlevels)
                     ).astype(int)
        inside = ((i >= 0) & (i < self.ncells) & (j >= 0)
                  & (j < self.ncells) & (k >= 0) & (k < self.nlevels))
        return np.where(inside, i, -1), np.where(inside, j, -1), \
            np.where(inside, k, -1), inside


def coca_counts(area, lat, lon, alt, active):
    """Cell-occupancy histogram [ncells, ncells, nlevels] + summary
    (metric_CoCa.applyMetric:346-505, vectorized)."""
    i, j, k, inside = area.cell_indices(lat, lon, alt)
    sel = inside & active
    counts = np.zeros((area.ncells, area.ncells, area.nlevels), int)
    np.add.at(counts, (i[sel], j[sel], k[sel]), 1)
    return counts


def hb_complexity(lat, lon, alt, tas, trk, active,
                  ctrlat, ctrlon, radius_nm,
                  dist_range_nm=5.0, alt_range_ft=1000.0,
                  time_lookahead=1800.0):
    """Two-circle conflict-geometry complexity (metric_HB:580-1300).

    Counts encounter pairs inside the FIR circle whose CPA lies within
    ``dist_range_nm`` / ``alt_range_ft`` inside the lookahead, and the
    per-aircraft share involved.  Returns (complexity, n_selected,
    compl_ac).
    """
    from ..ops.geo import kwikdist_wrapped
    d_fir = kwikdist_wrapped(ctrlat, ctrlon, lat, lon, xp=np)
    sel = active & (np.asarray(d_fir) < radius_nm)
    n = int(sel.sum())
    if n < 2:
        return 0, n, 0
    lat, lon = lat[sel], lon[sel]
    alt, tas, trk = alt[sel], tas[sel], trk[sel]

    # Flat-earth relative geometry (the HB method works in nm around
    # the FIR anchor, metric.py:595-612)
    coslat = np.cos(np.radians(ctrlat))
    x = (lon - ctrlon) * 60.0 * coslat          # [nm]
    y = (lat - ctrlat) * 60.0
    vx = tas / NM * np.sin(np.radians(trk))     # [nm/s]
    vy = tas / NM * np.cos(np.radians(trk))

    dx = x[None, :] - x[:, None]
    dy = y[None, :] - y[:, None]
    dvx = vx[None, :] - vx[:, None]
    dvy = vy[None, :] - vy[:, None]
    dv2 = dvx * dvx + dvy * dvy
    dv2 = np.where(dv2 < 1e-12, 1e-12, dv2)
    tcpa = -(dvx * dx + dvy * dy) / dv2
    dcpa2 = (dx + dvx * tcpa) ** 2 + (dy + dvy * tcpa) ** 2
    dalt = np.abs(alt[None, :] - alt[:, None]) / FT

    enc = ((tcpa > 0.0) & (tcpa < time_lookahead)
           & (dcpa2 < dist_range_nm ** 2) & (dalt < alt_range_ft))
    np.fill_diagonal(enc, False)
    complexity = int(enc.sum()) // 2            # unique pairs
    compl_ac = int(enc.any(axis=1).sum())
    return complexity, n, compl_ac


class Metrics:
    """Coordinator (reference Metric:1311-1443): periodic evaluation of
    the selected metric, CSV logging, METRICS stack command."""

    NAMES = ("CoCa-Metric", "HB-Metric")

    def __init__(self, sim):
        self.sim = sim
        self.metric_number = -1      # -1 = off
        self.dt = 1.0
        self.tnext = 0.0
        self.area = MetricsArea()
        self.fir_circle_point = (52.6, 5.4)
        self.fir_circle_radius = 230.0     # [nm]
        from ..utils import datalog
        self.logger = datalog.defineLogger(
            "METLOG",
            "Metrics log: metric name, then metric-specific columns")

    # ------------------------------------------------------------ command
    def toggle(self, flag=None, dt=None):
        """METRICS OFF / METRICS n [dt] (Metric.toggle:1358-1387)."""
        if flag is None:
            state = "OFF" if self.metric_number < 0 \
                else self.NAMES[self.metric_number]
            return True, f"METRICS {state} (dt={self.dt})"
        if isinstance(flag, str) and flag.upper() in ("OFF", "0"):
            self.metric_number = -1
            return True, "Metrics OFF"
        try:
            num = int(float(flag))
        except (TypeError, ValueError):
            return False, "METRICS OFF or METRICS 1/2 [dt]"
        if num <= 0:
            self.metric_number = -1
            return True, "Metrics OFF"
        if num > len(self.NAMES):
            return False, "No such metric"
        if dt is not None:
            self.dt = float(dt)
        self.metric_number = num - 1
        self.tnext = self.sim.simt
        if not self.logger.active:
            self.logger.start(self.sim)
        return True, (f"Activated {self.NAMES[self.metric_number]} "
                      f"({num}), dt={self.dt:.2f}")

    # ------------------------------------------------------------- update
    def update(self):
        """Evaluate the active metric when due (chunk edges)."""
        if self.metric_number < 0:
            return
        t = self.sim.simt
        if t < self.tnext - 1e-9:
            return
        self.tnext = t + self.dt
        st = self.sim.traf.state.ac
        active = np.asarray(st.active)
        lat = np.asarray(st.lat)
        lon = np.asarray(st.lon)
        alt = np.asarray(st.alt)
        if self.metric_number == 0:
            counts = coca_counts(self.area, lat, lon, alt, active)
            self.last_counts = counts
            self.logger.log(self.sim, ["CoCa"], [int(counts.sum())],
                            [int(counts.max())],
                            [float(counts[counts > 0].mean())
                             if (counts > 0).any() else 0.0])
        else:
            tas = np.asarray(st.tas)
            trk = np.asarray(st.trk)
            cx, n, cac = hb_complexity(
                lat, lon, alt, tas, trk, active,
                self.fir_circle_point[0], self.fir_circle_point[1],
                self.fir_circle_radius)
            self.last_hb = (cx, n, cac)
            self.logger.log(self.sim, ["HB"], [cx], [n], [cac])

    def reset(self):
        self.metric_number = -1
        self.tnext = 0.0
