"""Pilot arbitration: choose AP or ASAS targets, apply envelope limits.

Parity with reference ``bluesky/traffic/pilot.py``: per-aircraft selection of
the conflict-resolution command set when ASAS is active (pilot.py:28-63),
wind-drift heading correction, and envelope limiting through the performance
model (pilot.py:65-82, OpenAP path).
"""
import jax.numpy as jnp

from . import perf as perfmod
from .state import SimState


def ap_or_asas(state: SimState, windn=None, winde=None) -> SimState:
    """Arbitrate desired states from ASAS (in conflict) or AP (pilot.py:28-63)."""
    ac, ap, asas = state.ac, state.ap, state.asas

    if windn is not None:
        # ASAS commands ground-frame velocities; convert to TAS by removing
        # the wind vector (pilot.py:31-35).
        asastasnorth = asas.tas * jnp.cos(jnp.radians(asas.trk)) - windn
        asastaseast = asas.tas * jnp.sin(jnp.radians(asas.trk)) - winde
        asastas = jnp.sqrt(asastasnorth ** 2 + asastaseast ** 2)
    else:
        asastas = asas.tas

    active = asas.active
    trk = jnp.where(active, asas.trk, ap.trk)
    tas = jnp.where(active, asastas, ap.tas)
    alt = jnp.where(active, asas.alt, ap.alt)
    vs = jnp.where(active, asas.vs, ap.vs)
    # Sign of VS is reapplied from the altitude error in the kinematics;
    # keep the magnitude only (pilot.py:46-48).
    vs = jnp.abs(vs)

    if windn is not None:
        vw = jnp.sqrt(windn * windn + winde * winde)
        winddir = jnp.arctan2(winde, windn)
        drift = jnp.radians(trk) - winddir
        steer = jnp.arcsin(jnp.clip(
            vw * jnp.sin(drift) / jnp.maximum(0.001, ac.tas), -1.0, 1.0))
        hdg = (trk + jnp.degrees(steer)) % 360.0
    else:
        hdg = trk % 360.0

    pilot = state.pilot.replace(trk=trk, tas=tas, alt=alt, vs=vs, hdg=hdg)
    return state.replace(pilot=pilot)


def apply_limits(state: SimState, smooth=None) -> SimState:
    """Clip pilot intents to the performance envelope (pilot.py:65-68).

    ``smooth`` threads the differentiable-mode straight-through clamp
    choice into ``perf.limits`` (None — the serving default — is the
    exact hard clip)."""
    pilot = state.pilot
    tas, vs, alt = perfmod.limits(state.perf, pilot.tas, pilot.vs, pilot.alt,
                                  state.ac.ax, smooth=smooth)
    return state.replace(pilot=pilot.replace(tas=tas, vs=vs, alt=alt))
