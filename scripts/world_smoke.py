"""CI perf-smoke: W=8 packed-BATCH parity run, journal-verified.

Drives the REAL serving fabric in one process: a broker with world
packing on, one SimNode worker, and a BATCH of 8 compatible pieces.
Verifies the three multi-world serving contracts cheaply enough for
every PR (the perf-smoke lane, .github/workflows/ci.yml):

1. the 8 pieces dispatch as ONE world-batch to the single worker;
2. the journal demux is exactly-once: replay owes nothing, every
   piece completed exactly once;
3. bit-exact parity: each world's final state equals an independent
   single-piece Simulation run of the same scenario.

Exits non-zero on any violation.
"""
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

W = 8


def main():
    import numpy as np
    import jax

    from bluesky_tpu.network.client import Client
    from bluesky_tpu.network.journal import BatchJournal
    from bluesky_tpu.network.server import Server
    from bluesky_tpu.simulation.simnode import SimNode
    from tests.test_network import free_ports, wait_for

    # WORLD_SMOKE_TRACE=1: run the whole pass with the flight recorder
    # ON (obs/trace.py) — the parity check below then doubles as the
    # proof that tracing never perturbs the stepped state — and leave a
    # merged Perfetto trace behind as a CI artifact.
    traced = os.environ.get("WORLD_SMOKE_TRACE") == "1"
    if traced:
        import bluesky_tpu.settings as settings
        from bluesky_tpu.obs.trace import get_recorder
        settings.trace_dir = os.path.join("output", "obs")
        get_recorder().enable()

    tmp = tempfile.mkdtemp(prefix="world-smoke-")
    scn = os.path.join(tmp, "mc.scn")
    with open(scn, "w") as f:
        for i in range(W):
            f.write(f"00:00:00.00>SCEN CASE_{i}\n")
            f.write(f"00:00:00.00>CRE AC{i} B744 {48 + i} {3 + i} "
                    f"{30 * i} FL200 250\n")
            f.write("00:00:00.00>FF 5\n")
    journal = os.path.join(tmp, "batch.jsonl")

    ev, st, wev, wst = free_ports(4)
    server = Server(headless=True,
                    ports=dict(event=ev, stream=st, wevent=wev,
                               wstream=wst),
                    spawn_workers=False, world_pack=True,
                    world_batch_max=W, journal_path=journal)
    server.start()
    time.sleep(0.2)
    node = SimNode(event_port=wev, stream_port=wst, nmax=16)
    t = threading.Thread(target=node.run, daemon=True)
    t.start()
    client = Client()
    client.connect(event_port=ev, stream_port=st, timeout=5.0)
    try:
        assert wait_for(lambda: (client.receive(10),
                                 len(client.nodes) >= 1)[1]), \
            "worker never registered"
        # keep a handle on the runner before it retires: poll for it
        client.stack(f"BATCH {scn}")
        runner = {}

        def catch_runner():
            client.receive(10)
            if node.worlds is not None:
                runner["wb"] = node.worlds
            return server.packed_pieces == W and not server.inflight \
                and not server.scenarios
        assert wait_for(catch_runner, timeout=300), "pack never drained"
        assert server.world_batches == 1, \
            f"expected 1 world-batch, got {server.world_batches}"
        wb = runner.get("wb")
        assert wb is not None and wb.nworlds == W

        state = BatchJournal.replay(journal)
        assert len(state["completed"]) == W and not state["pending"], \
            (f"journal demux not exactly-once: "
             f"{len(state['completed'])} completed, "
             f"{len(state['pending'])} pending")
        print(f"world-smoke: journal exactly-once OK "
              f"({W} completed, 0 pending)")

        # bit-exact parity vs independent single-piece runs
        from bluesky_tpu.simulation.sim import Simulation, OP
        piece_cmds = [[f"SCEN CASE_{i}",
                       f"CRE AC{i} B744 {48 + i} {3 + i} {30 * i} "
                       "FL200 250", "FF 5"] for i in range(W)]
        for i in range(W):
            ref = Simulation(nmax=16)
            ref.pipeline_enabled = False
            ref.stack.set_scendata([0.0] * 3, piece_cmds[i])
            ref.op()
            it = 0
            while ref.state_flag == OP and it < 5000:
                ref.step()
                it += 1
            got = wb.sims[i].traf.state
            for a, b in zip(jax.tree_util.tree_leaves(ref.traf.state),
                            jax.tree_util.tree_leaves(got)):
                assert np.array_equal(np.asarray(a), np.asarray(b),
                                      equal_nan=True), \
                    f"world {i}: packed state != solo state"
        print(f"world-smoke: W={W} packed-vs-solo state parity OK")
        if traced:
            # one in-process recorder covers the worker AND the broker
            # thread (tid separates the tracks); merge the dump so the
            # artifact opens directly in the Perfetto UI
            import json as _json
            from bluesky_tpu.obs.trace import get_recorder
            import trace_report
            rec = get_recorder()
            path = rec.dump(reason="world_smoke", proc="fabric")
            assert path, "traced pass left an empty recorder ring"
            events = trace_report.load([path])
            names = {e["name"] for e in events}
            assert "chunk_dispatch" in names, \
                f"traced pass recorded no dispatch spans: {sorted(names)}"
            merged = os.path.join("output", "obs",
                                  "world_smoke_trace.json")
            with open(merged, "w") as f:
                _json.dump(trace_report.merge(events), f)
            print(f"world-smoke: traced pass OK — {len(events)} events "
                  f"-> {merged}")
        print("world-smoke: PASS")
    finally:
        node.quit()
        t.join(timeout=5)
        server.stop()
        server.join(timeout=5)
        client.close()


if __name__ == "__main__":
    main()
