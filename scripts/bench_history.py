"""Perf-regression sentinel over BENCH_HISTORY.jsonl (ISSUE-12).

Every ``bench.write_bench_json`` call appends its measured rows to an
append-only history file, one JSON line per row::

    {"series": "BENCH_OBS", "ts": ..., "git_rev": "abc1234",
     "platform": "cpu:cpu", "row": {...}}

``compare`` groups the lines by (series, platform, row identity) —
identity being the protocol fields that define *which* configuration a
row measures (n, backend, geometry, worlds, chunk length, pipeline
mode, ...) — and diffs each group's NEWEST row against the baseline
built from the earlier rows (median per metric, so one noisy run
doesn't poison the gate).  A metric that moved in its bad direction by
more than ``--threshold`` is a regression: the run exits 1 and prints
a structured report naming every regressed row.

Run:
    python scripts/bench_history.py compare [HISTORY.jsonl]
        [--threshold 0.10] [--series BENCH_OBS] [--report out.json]
    python scripts/bench_history.py list [HISTORY.jsonl]

CI wires ``compare`` into the perf-smoke lane (non-blocking until the
baseline has three green runs; see .github/workflows/ci.yml).
"""
import argparse
import json
import statistics
import sys

# Protocol fields that identify WHICH configuration a row measures —
# rows only compare within a group that agrees on all of these.
IDENTITY_FIELDS = ("n", "backend", "geometry", "worlds", "mode",
                   "scenario", "nsteps_chunk", "nsteps", "chunk",
                   "pipeline", "shard", "shard_devices", "tile_shape",
                   "protocol", "dense", "D")

# Metric -> direction: +1 = higher is better, -1 = lower is better.
METRICS = {
    "ac_steps_per_s": +1,
    "ac_steps_per_s_unguarded": +1,
    "ac_steps_per_s_guarded": +1,
    "x_realtime": +1,
    "x_realtime_per_world": +1,
    "gap_vs_ff": +1,
    "speedup": +1,
    "pairs_per_s_per_device": +1,
    "overhead_pct": -1,
    "wall_s": -1,
    "wall_off_s": -1,
    "wall_on_s": -1,
    "bwd_over_fwd": -1,
    "smooth_over_hard": -1,
    "imbalance": -1,
    "kernel_ms_dev": -1,
    # 2-D tile decomposition (ISSUE 19): halo exchange volume per
    # device and wire totals must not creep up; occupancy headroom
    # (occ = fullest tile / even split) must not drift toward the
    # shard cap.  tile_shape is an IDENTITY field — a 4x2 row never
    # compares against a 4x4 row.
    "wire_mb_dev": -1,
    "halo_bytes_dev": -1,
    "halo_rows": -1,
    "occ": -1,
}


def load(path):
    """Read the history file; bad lines are skipped with a warning so
    one torn append can't disable the sentinel."""
    entries = []
    try:
        with open(path) as f:
            for i, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    e = json.loads(line)
                except ValueError:
                    print(f"{path}:{i}: unparseable line skipped",
                          file=sys.stderr)
                    continue
                if isinstance(e, dict) and isinstance(e.get("row"),
                                                      dict):
                    entries.append(e)
    except OSError as e:
        print(f"cannot read {path}: {e}", file=sys.stderr)
    return entries


def identity(entry):
    row = entry["row"]
    ident = tuple((k, row[k]) for k in IDENTITY_FIELDS if k in row)
    return (entry.get("series", "?"),
            entry.get("platform", row.get("platform", "?")), ident)


def group(entries):
    groups = {}
    for e in entries:
        groups.setdefault(identity(e), []).append(e)
    for g in groups.values():
        g.sort(key=lambda e: e.get("ts", 0.0))
    return groups


def compare(entries, threshold=0.10, series=None):
    """Newest row per group vs the median of the earlier rows.
    Returns (regressions, checked_groups)."""
    regressions, checked = [], 0
    for (ser, platform, ident), g in sorted(group(entries).items()):
        if series and ser != series:
            continue
        if len(g) < 2:
            continue              # no baseline yet
        newest, base = g[-1], g[:-1]
        checked += 1
        for metric, direction in METRICS.items():
            nv = newest["row"].get(metric)
            bvals = [b["row"][metric] for b in base
                     if isinstance(b["row"].get(metric), (int, float))]
            if not isinstance(nv, (int, float)) or not bvals:
                continue
            bv = statistics.median(bvals)
            if not bv:
                continue
            change = (nv - bv) / abs(bv)
            if change * direction < -threshold:
                regressions.append({
                    "series": ser, "platform": platform,
                    "identity": dict(ident), "metric": metric,
                    "baseline": bv, "newest": nv,
                    "change_pct": round(change * 100.0, 1),
                    "baseline_runs": len(bvals),
                    "git_rev": newest.get("git_rev", "?"),
                })
    return regressions, checked


def cmd_list(entries):
    for (ser, platform, ident), g in sorted(group(entries).items()):
        tag = " ".join(f"{k}={v}" for k, v in ident)
        print(f"{ser:>20} [{platform}] {len(g):>3} run(s)  {tag}")
    return 0


def cmd_compare(entries, threshold, series, report_path):
    regressions, checked = compare(entries, threshold, series)
    report = {"checked_groups": checked,
              "threshold_pct": round(threshold * 100.0, 1),
              "regressions": regressions}
    if report_path:
        with open(report_path, "w") as f:
            json.dump(report, f, indent=1)
    if not regressions:
        print(f"bench history OK: {checked} series group(s) within "
              f"{threshold * 100:.0f}% of baseline")
        return 0
    print(f"PERF REGRESSION: {len(regressions)} metric(s) past the "
          f"{threshold * 100:.0f}% gate", file=sys.stderr)
    for r in regressions:
        tag = " ".join(f"{k}={v}" for k, v in r["identity"].items())
        print(f"  {r['series']} [{r['platform']}] {tag}: "
              f"{r['metric']} {r['baseline']:g} -> {r['newest']:g} "
              f"({r['change_pct']:+.1f}%, rev {r['git_rev']})",
              file=sys.stderr)
    print(json.dumps(report), file=sys.stderr)
    return 1


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("command", choices=("compare", "list"))
    ap.add_argument("history", nargs="?", default="BENCH_HISTORY.jsonl")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="fractional regression gate (default 0.10)")
    ap.add_argument("--series", default=None,
                    help="restrict to one series (e.g. BENCH_OBS)")
    ap.add_argument("--report", default=None,
                    help="write the structured JSON report here")
    args = ap.parse_args(argv)

    entries = load(args.history)
    if not entries:
        # An absent/empty history is not a regression — the sentinel
        # has nothing to say until two runs of one series exist.
        print(f"no history entries in {args.history}; nothing to do")
        return 0
    if args.command == "list":
        return cmd_list(entries)
    return cmd_compare(entries, args.threshold, args.series,
                       args.report)


if __name__ == "__main__":
    sys.exit(main())
