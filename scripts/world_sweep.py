"""Multi-world throughput sweep -> BENCH_WORLDS.json.

Measures aggregate aircraft-steps/s of the world-batched scan
(core/step.run_steps_worlds: one stacked vmapped chunk steps W
scenarios) against the one-piece-per-worker baseline (the same
compiled single-world program dispatched serially — the chip-time a
worker-process fleet sharing one device gets), for W x N in the
small-scenario serving regime the packing layer targets (N in
{100, 500, 2000}).

Every measured row is platform-tagged (the repo's bench convention:
tpu:v5e history and cpu:cpu rows coexist).  On a CPU-only box the
measured ratio is bounded by the core count — a single core is
compute-saturated by ONE world, so batching mostly amortizes per-op
overheads (SURVEY: the 10x regime is idle accelerator lanes).  The
file therefore also carries a CALIBRATED chip projection for the
headline 256 x N=500 fleet, derived from this repo's own TPU-measured
BENCH_DETAIL.json rows: a [256*500 = 128k]-row batched program runs at
the measured N~100k sparse/continental efficiency, while the
one-piece-per-worker fleet pays the measured small-N per-dispatch rate
— the same calibration idiom as BENCH_FULL_INTERVAL.json's projected
spatial rows.

``--quick`` runs the tiny CI matrix (perf-smoke lane).
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def sweep(quick=False):
    import bench

    platform = bench.platform_tag()
    rows = []
    if quick:
        matrix = {100: ((8, 100),), 500: ((8, 50),)}
        reps = 1
    else:
        # W caps bound dense [W,N,N] CD temporaries + wall time on the
        # sweep box; every cap is recorded in the emitted row (no
        # silent coverage cuts)
        matrix = {
            100: ((4, 200), (16, 200), (64, 200), (256, 100)),
            500: ((4, 100), (16, 60), (64, 60), (256, 40)),
            2000: ((4, 40), (16, 30), (32, 30)),
        }
        reps = 1
    w_cap = {2000: 32}
    for n, wspecs in matrix.items():
        baseline = None
        for w, nsteps in wspecs:
            row, base = bench.run_worlds(n, w, nsteps=nsteps, reps=reps)
            row["platform"] = base["platform"] = platform
            if n in w_cap:
                row["w_cap"] = w_cap[n]
                row["w_cap_reason"] = ("dense [W,N,N] CD temporaries: "
                                       f"{w_cap[n]}x{n}^2 f32 bounds "
                                       "sweep-box memory")
            if baseline is None:
                baseline = base
                rows.append(base)
            rows.append(row)
            print(json.dumps(row), flush=True)
    return rows, platform


def chip_projection():
    """Calibrated accelerator projection for the 256 x N=500 headline,
    from this repo's own TPU-measured BENCH_DETAIL.json rows (same
    idiom as BENCH_FULL_INTERVAL.json's projected spatial column) —
    conservative on BOTH ends:

    * one-piece-per-worker baseline: each dispatch runs a SMALL-N
      program whose per-step wall time is fixed-cost (latency) bound on
      the chip; the measured dense N=1000/regional ac-steps/s is an
      UPPER bound on an N=500 dispatch (same per-step latency, half
      the rows per step).
    * world-batched: one [256 x 500 = 128k]-row program; the measured
      sparse N~100k/global row OVERSTATES its cost — 256 independent
      500-aircraft worlds have ZERO cross-world pairs (the vmapped CD
      is within-world by construction, ~32M reachable pairs/interval
      total), less CD work than even the lowest-density measured 100k
      single fleet.
    """
    try:
        detail = json.load(open(os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "BENCH_DETAIL.json")))
    except OSError:
        return None
    byrow = {(r["n"], r["backend"], r["geometry"]):
             r["ac_steps_per_s"] for r in detail if not r.get("failed")}
    base = byrow.get((1000, "dense", "regional"))
    batched = byrow.get((100000, "sparse", "global"))
    if not base or not batched:
        return None
    return {
        "n": 500, "worlds": 256, "projected": True,
        "platform": "tpu:v5e (calibrated from BENCH_DETAIL.json)",
        "baseline_ac_steps_per_s": base,
        "baseline_basis": "measured dense N=1000 regional row — an "
                          "UPPER bound on an N=500 per-dispatch rate "
                          "(same fixed per-step latency, half the "
                          "rows)",
        "batched_ac_steps_per_s": batched,
        "batched_basis": "measured sparse N=100k global row — "
                         "OVERSTATES the 128k-row batch's cost (256 "
                         "independent worlds carry zero cross-world "
                         "pairs, so less CD work than any measured "
                         "100k single fleet)",
        "speedup": round(batched / base, 1),
    }


def main():
    import bench
    quick = "--quick" in sys.argv
    path = bench.pop_out_flag(sys.argv, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "BENCH_WORLDS.json"))
    reproject = "--reproject" in sys.argv
    if reproject:
        # refresh the calibrated projection/headline over the existing
        # measured rows without re-running the sweep
        old = json.load(open(path))
        rows = old["rows"]
        platform = next((r["platform"] for r in rows
                         if "platform" in r), "cpu:cpu")
    else:
        rows, platform = sweep(quick=quick)
    # measured headline: the largest N=500 batched row vs its baseline
    measured = None
    n500 = [r for r in rows if r["n"] == 500 and r.get("worlds", 1) > 1]
    if n500:
        best = max(n500, key=lambda r: r["worlds"])
        measured = {
            "platform": platform, "n": 500, "worlds": best["worlds"],
            "speedup": best.get("speedup"),
            "note": ("single-core CPU boxes are compute-saturated by "
                     "one world; the >=10x regime is idle accelerator "
                     "lanes — see projected_chip_headline")
            if platform.startswith("cpu") else None,
        }
    # shared tagging + writing boilerplate lives in bench.py now; a
    # reprojection re-derives headlines over rows that were already
    # recorded, so it must not double-append to BENCH_HISTORY (keeps
    # --reproject round-trips byte-identical on the JSON too)
    bench.write_bench_json(path, rows, history=not reproject,
                           projected_chip_headline=chip_projection(),
                           measured_headline=measured)


if __name__ == "__main__":
    main()
