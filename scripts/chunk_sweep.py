"""Measure the headline's sensitivity to the scan-chunk length, with
the async chunk pipeline ON and OFF (VERDICT r4 #6 + ISSUE 4).

Runs the bench.run_chunked protocol — the production Simulation.step
cost model: per-chunk host re-sort, per-edge telemetry consumption —
at chunk = 20 / 100 / 400 / 1000 steps over the same total step count,
and emits one JSON row per (chunk, pipeline) cell including the
host-edge overhead breakdown (dispatch gap + telemetry-pull time per
chunk).  20 is the production interactive default, 1000 the FF/BATCH
headline protocol; the pipeline's job is to close the gap between
them.

Rows land in output/chunk_sweep.json AND are merged into the repo-root
BENCH_CHUNK_SWEEP.json: rows from other platforms (e.g. the historical
TPU v5e sweep) are kept, rows for the current platform are replaced.

Usage: python scripts/chunk_sweep.py [N] [--pipeline on|off|both]
       [--total-steps S]
"""
import json
import os
import sys

sys.path.insert(0, ".")

import bench  # noqa: E402


def main(n_ac=100_000, pipeline="both", total_steps=1000):
    modes = {"on": [True], "off": [False],
             "both": [False, True]}[pipeline]
    plat = bench.platform_tag()
    rows = []
    for nsteps in (20, 100, 400, 1000):
        for pipe in modes:
            r = bench.run_chunked(n_ac, backend=None,
                                  geometry="continental", chunk=nsteps,
                                  total_steps=max(total_steps, nsteps),
                                  pipeline=pipe, reps=3)
            r["platform"] = plat
            rows.append(r)
            print(json.dumps(r), flush=True)
    # fresh checkout: output/ may not exist yet — a multi-minute run
    # must not crash at the final dump
    os.makedirs("output", exist_ok=True)
    with open("output/chunk_sweep.json", "w") as f:
        json.dump(rows, f, indent=1)
    merge_bench_file(rows, plat)
    return rows


def merge_bench_file(rows, plat, path="BENCH_CHUNK_SWEEP.json"):
    """Replace this platform's rows in BENCH_CHUNK_SWEEP.json, keep the
    rest (the historical TPU sweep stays on record when re-running on
    CPU and vice versa).  Writes through the shared bench writer; only
    the NEW rows go to BENCH_HISTORY (the kept rows were recorded by
    the run that measured them)."""
    old = []
    if os.path.isfile(path):
        try:
            with open(path) as f:
                old = json.load(f)
        except (OSError, ValueError):
            old = []
    if isinstance(old, dict):               # shared writer format
        old = old.get("rows", [])
    kept = [r for r in old if r.get("platform", "tpu:v5e") != plat]
    bench.write_bench_json(path, kept + rows, history=False)
    bench.append_history(os.path.splitext(os.path.basename(path))[0],
                         rows, tag=plat)


if __name__ == "__main__":
    # positional parse: consume each flag's value by INDEX, never by
    # textual equality (``chunk_sweep.py 400 --total-steps 400`` must
    # keep N=400)
    argv = sys.argv[1:]
    pipeline = "both"
    total = 1000
    if "--pipeline" in argv:
        i = argv.index("--pipeline")
        pipeline = argv[i + 1].lower()
        del argv[i:i + 2]
    if "--total-steps" in argv:
        i = argv.index("--total-steps")
        total = int(argv[i + 1])
        del argv[i:i + 2]
    args = [a for a in argv if not a.startswith("--")]
    main(int(args[0]) if args else 100_000, pipeline=pipeline,
         total_steps=total)
