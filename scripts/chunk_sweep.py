"""VERDICT r4 #6: measure the 100k-continental headline's sensitivity to
the scan-chunk length (refresh + dispatch amortization vs chunk).

Runs the exact bench.run_one protocol at chunk = 20 / 100 / 400 / 1000
steps (20 is the production Simulation default, 1000 the FF/BATCH
headline protocol) and prints one JSON line per row; the table lands in
docs/PERF_ANALYSIS.md and the protocol fields in BENCH_DETAIL rows.

Usage: python scripts/chunk_sweep.py [N]
"""
import json
import os
import sys

sys.path.insert(0, ".")

import bench  # noqa: E402


def main(n_ac=100_000):
    rows = []
    for nsteps in (20, 100, 400, 1000):
        r = bench.run_one(n_ac, backend=None, geometry="continental",
                          nsteps=nsteps, reps=3)
        r["nsteps_chunk"] = nsteps
        r["protocol"] = "best-of-3, host re-sort per chunk"
        rows.append(r)
        print(json.dumps(r), flush=True)
    # fresh checkout: output/ may not exist yet — a multi-minute run
    # must not crash at the final dump
    os.makedirs("output", exist_ok=True)
    with open("output/chunk_sweep.json", "w") as f:
        json.dump(rows, f, indent=1)
    return rows


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 100_000)
