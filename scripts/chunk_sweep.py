"""Measure the headline's sensitivity to the scan-chunk length, with
the async chunk pipeline ON and OFF (VERDICT r4 #6 + ISSUE 4).

Runs the bench.run_chunked protocol — the production Simulation.step
cost model: per-chunk host re-sort, per-edge telemetry consumption —
at chunk = 20 / 100 / 400 / 1000 steps over the same total step count,
and emits one JSON row per (chunk, pipeline) cell including the
host-edge overhead breakdown (dispatch gap + telemetry-pull time per
chunk).  20 is the production interactive default, 1000 the FF/BATCH
headline protocol; the pipeline's job is to close the gap between
them.

Every row carries a ``gap_vs_ff`` column (ISSUE 15): its x_realtime
divided by the best x_realtime of the LARGEST-chunk row in the same
(platform, backend, n, pipeline) group — 1.0 is "no interactive-chunk
penalty vs the FF/BATCH headline", the tpu:v5e 20-step host-re-sort
row sits at ~0.30.  ``--inscan on|both`` additionally measures the
in-scan sort-refresh protocol (sparse backend only): the refresh folds
into the compiled chunk, so short chunks stop paying a host refresh
dispatch per edge.

Rows land in output/chunk_sweep.json AND are merged into the repo-root
BENCH_CHUNK_SWEEP.json: rows are replaced per (platform, backend, n)
triple, everything else (e.g. the historical TPU v5e sweep, the CPU
dense sweep) is kept — and the gap_vs_ff column is (re)derived across
the merged set so kept rows get it too.

Usage: python scripts/chunk_sweep.py [N] [--pipeline on|off|both]
       [--total-steps S] [--backend sparse|dense|tiled|pallas]
       [--inscan on|off|both]
"""
import json
import os
import sys

sys.path.insert(0, ".")

import bench  # noqa: E402


def main(n_ac=100_000, pipeline="both", total_steps=1000,
         backend=None, inscan="off"):
    modes = {"on": [True], "off": [False],
             "both": [False, True]}[pipeline]
    inscan_modes = {"on": [True], "off": [False],
                    "both": [False, True]}[inscan]
    plat = bench.platform_tag()
    rows = []
    for nsteps in (20, 100, 400, 1000):
        for pipe in modes:
            for isc in inscan_modes:
                r = bench.run_chunked(n_ac, backend=backend,
                                      geometry="continental",
                                      chunk=nsteps,
                                      total_steps=max(total_steps,
                                                      nsteps),
                                      pipeline=pipe, reps=3,
                                      inscan=isc)
                r["platform"] = plat
                rows.append(r)
                print(json.dumps(r), flush=True)
    add_gap_vs_ff(rows)
    # fresh checkout: output/ may not exist yet — a multi-minute run
    # must not crash at the final dump
    os.makedirs("output", exist_ok=True)
    with open("output/chunk_sweep.json", "w") as f:
        json.dump(rows, f, indent=1)
    merge_bench_file(rows, plat)
    return rows


def _gap_group(r):
    return (r.get("platform", "tpu:v5e"), r.get("backend"),
            r.get("n"), r.get("pipeline"))


def add_gap_vs_ff(rows):
    """Annotate rows with ``gap_vs_ff``: x_realtime over the best
    x_realtime among the group's largest-chunk rows.  Grouping is
    (platform, backend, n, pipeline) — deliberately NOT protocol, so
    an in-scan 20-step row is measured against the same FF denominator
    as the host-re-sort row it is trying to beat, and a model-projected
    row normalises against the measured headline."""
    groups = {}
    for r in rows:
        groups.setdefault(_gap_group(r), []).append(r)
    for g in groups.values():
        chunks = [r["nsteps_chunk"] for r in g
                  if isinstance(r.get("nsteps_chunk"), (int, float))]
        if not chunks:
            continue
        cmax = max(chunks)
        ff = max((r.get("x_realtime") or 0.0) for r in g
                 if r.get("nsteps_chunk") == cmax)
        if not ff:
            continue
        for r in g:
            if isinstance(r.get("x_realtime"), (int, float)):
                r["gap_vs_ff"] = round(r["x_realtime"] / ff, 3)
    return rows


def merge_bench_file(rows, plat, path="BENCH_CHUNK_SWEEP.json"):
    """Replace matching (platform, backend, n) rows in
    BENCH_CHUNK_SWEEP.json, keep the rest (the historical TPU sweep
    and the CPU dense sweep stay on record when re-running one config).
    The gap_vs_ff column is re-derived over the merged set so kept
    rows gain it retroactively.  Writes through the shared bench
    writer; only the NEW rows go to BENCH_HISTORY (the kept rows were
    recorded by the run that measured them)."""
    old = []
    if os.path.isfile(path):
        try:
            with open(path) as f:
                old = json.load(f)
        except (OSError, ValueError):
            old = []
    if isinstance(old, dict):               # shared writer format
        old = old.get("rows", [])
    new_keys = {(r.get("platform", plat), r.get("backend"), r.get("n"))
                for r in rows}
    kept = [r for r in old
            if (r.get("platform", "tpu:v5e"), r.get("backend"),
                r.get("n")) not in new_keys]
    merged = add_gap_vs_ff(kept + rows)
    bench.write_bench_json(path, merged, history=False)
    bench.append_history(os.path.splitext(os.path.basename(path))[0],
                         rows, tag=plat)


if __name__ == "__main__":
    # positional parse: consume each flag's value by INDEX, never by
    # textual equality (``chunk_sweep.py 400 --total-steps 400`` must
    # keep N=400)
    argv = sys.argv[1:]
    pipeline = "both"
    total = 1000
    backend = None
    inscan = "off"
    if "--pipeline" in argv:
        i = argv.index("--pipeline")
        pipeline = argv[i + 1].lower()
        del argv[i:i + 2]
    if "--total-steps" in argv:
        i = argv.index("--total-steps")
        total = int(argv[i + 1])
        del argv[i:i + 2]
    if "--backend" in argv:
        i = argv.index("--backend")
        backend = argv[i + 1].lower()
        del argv[i:i + 2]
    if "--inscan" in argv:
        i = argv.index("--inscan")
        inscan = argv[i + 1].lower()
        del argv[i:i + 2]
    args = [a for a in argv if not a.startswith("--")]
    main(int(args[0]) if args else 100_000, pipeline=pipeline,
         total_steps=total, backend=backend, inscan=inscan)
