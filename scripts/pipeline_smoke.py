"""CI perf-smoke: the pipelined chunk loop must be bit-identical to
synchronous stepping, and the fused edge-telemetry pack must round-trip
through the ACDATA stream schema.

Tiny N, CPU, seconds of wall time — run non-blocking in CI so a flaky
runner can't gate merges, but a real divergence is loud on every PR.

Exit 0 on success, 1 with a diagnostic on any mismatch.

``--inscan`` (ISSUE 15) runs the 20-step-chunk production loop on the
sparse backend twice — SORTREFRESH ON (refresh folded into the
compiled chunk) vs OFF (host re-sort at chunk edges) — with the
refresh cadence aligned to the chunk edge so both fire at identical
sim instants, and asserts the final states hash bit-identically and
the ON run performed zero host edge refreshes.

Usage: python scripts/pipeline_smoke.py [--inscan]
"""
import hashlib
import os
import sys

sys.path.insert(0, ".")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


def state_hash(sim):
    import jax
    h = hashlib.sha256()
    for leaf in jax.tree.leaves(jax.tree.map(np.asarray, sim.traf.state)):
        h.update(np.ascontiguousarray(leaf).tobytes())
    h.update(repr([sim.traf.ids, sim.traf.types]).encode())
    return h.hexdigest()


def build_and_run(pipeline: bool):
    from bluesky_tpu.simulation.sim import Simulation
    sim = Simulation(nmax=32)
    sim.pipeline_enabled = pipeline
    for cmd in (
            "CRE KL1 B744 52 4 90 FL200 250",
            "CRE KL2 B744 52.2 4.3 270 FL210 250",
            "SCHEDULE 00:00:03 ALT KL1 FL300",
            "SCHEDULE 00:00:06 CRE KL3 B744 53 5 180 FL100 200",
            "SCHEDULE 00:00:09 DEL KL2",
            "FF"):
        sim.stack.stack(cmd)
    sim.stack.process()
    sim.op()
    sim.run(until_simt=15.0, max_iters=1000)
    return sim


def check_parity():
    a = build_and_run(True)
    b = build_and_run(False)
    ha, hb = state_hash(a), state_hash(b)
    assert a.pipe_stats["pipelined_chunks"] > 0, \
        "pipelined run never actually pipelined"
    assert b.pipe_stats["pipelined_chunks"] == 0, \
        "sync run pipelined despite the toggle"
    assert ha == hb, (f"pipelined vs sync state hash diverged:\n"
                      f"  pipelined {ha}\n  sync      {hb}\n"
                      f"  simt {a.simt} vs {b.simt}")
    print(f"parity OK: hash {ha[:16]}..., simt {a.simt:.2f}, "
          f"{a.pipe_stats['pipelined_chunks']} pipelined chunks")
    return a


def check_telemetry_schema(sim):
    """The edge pack must cover every per-aircraft ACDATA field the
    stream schema test checks (test_stream_schema.py), and survive the
    network serializer round-trip."""
    edge = sim._last_edge
    assert edge is not None, "no retired edge after a pipelined run"
    idx, data = edge.acdata_arrays()
    data["simt"] = edge.simt
    data["id"] = [sim.traf.ids[i] for i in idx]
    data["nconf_cur"] = int(np.asarray(edge.nconf_cur)) // 2
    data["nlos_cur"] = int(np.asarray(edge.nlos_cur)) // 2
    required = {"lat", "lon", "alt", "trk", "tas", "gs", "cas", "vs",
                "inconf", "tcpamax", "asasn", "asase"}
    missing = required - set(data)
    assert not missing, f"edge pack missing ACDATA fields: {missing}"
    n = len(data["id"])
    for key in sorted(required):
        assert np.asarray(data[key]).shape == (n,), \
            f"{key}: shape {np.asarray(data[key]).shape} != ({n},)"
    # round-trip through the wire serializer the streams use
    try:
        from bluesky_tpu.network.npcodec import packb, unpackb
        raw = packb(data)
        back = unpackb(raw)
        for key in sorted(required):
            assert np.allclose(np.asarray(back[key]),
                               np.asarray(data[key])), key
        print(f"telemetry pack round-trips the stream codec "
              f"({len(raw)} bytes, {n} aircraft)")
    except ImportError:
        print("msgpack not installed — schema check ran, codec "
              "round-trip skipped")


def build_and_run_inscan(inscan: bool):
    """20-step-chunk production loop, sparse backend, refresh cadence
    ALIGNED to the chunk edge: period = sort_every * dtasas = 2.5 s =
    one 20-step chunk at simdt 0.125 (all dyadic, exact in f32).  The
    host-edge refresh (OFF) and the in-scan gate (ON) therefore fire
    at identical sim instants and the end states must match
    bit-for-bit."""
    from bluesky_tpu.simulation.sim import Simulation
    sim = Simulation(nmax=512, chunk_steps=20)
    rng = np.random.default_rng(7)
    n = 120
    sim.traf.create(n, "B744", rng.uniform(4900, 5100, n),
                    rng.uniform(140, 180, n), None,
                    rng.uniform(35, 60, n), rng.uniform(-10, 30, n),
                    rng.uniform(0, 360, n))
    sim.traf.flush()
    sim.cfg = sim.cfg._replace(
        simdt=0.125, cd_backend="sparse", cd_block=256,
        asas=sim.cfg.asas._replace(sort_every=2, dtasas=1.25))
    if inscan:
        assert sim.set_inscan_refresh(True), \
            "SORTREFRESH ON rejected (gate inactive?)"
    sim.op()
    sim.run(until_simt=10.0, max_iters=1000)
    sim.drain_pipeline()
    return sim


def check_inscan_parity():
    a = build_and_run_inscan(True)
    b = build_and_run_inscan(False)
    rh = a.refresh_health()
    assert rh["inscan_refreshes"] > 0, "in-scan gate never fired"
    assert rh["guard_trips"] == 0, f"refresh guard tripped: {rh}"
    h = a.obs.get("sim_sort_refresh_ms")
    assert h is None or int(h.count) == 0, \
        f"host edge refresh ran {h.count}x with in-scan ON"
    ha, hb = state_hash(a), state_hash(b)
    assert ha == hb, (f"in-scan vs host-refresh state hash diverged:\n"
                      f"  in-scan {ha}\n  host    {hb}\n"
                      f"  simt {a.simt} vs {b.simt}")
    print(f"in-scan refresh parity OK: hash {ha[:16]}..., "
          f"{rh['inscan_refreshes']} in-scan refreshes, 0 host edge "
          f"refreshes, simt {a.simt:.2f}")


def main():
    if "--inscan" in sys.argv:
        check_inscan_parity()
        print("pipeline smoke (in-scan) OK")
        return
    sim = check_parity()
    check_telemetry_schema(sim)
    print("pipeline smoke OK")


if __name__ == "__main__":
    try:
        main()
    except AssertionError as e:
        print(f"PIPELINE SMOKE FAILED: {e}", file=sys.stderr)
        sys.exit(1)
