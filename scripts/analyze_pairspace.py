"""Pair-space structure analysis at the north-star geometry (CPU only).

Quantifies, for N=100k continental (bench.py geometry):
  * brute-force pair count (N^2)
  * pairs surviving the current block-level reachability skip (256-blocks,
    Morton sort) — what the Pallas full-grid kernel computes today
  * pairs at sub-block (32) candidate granularity — what the mixed-mode
    candidate scheduler computes
  * the per-AIRCRAFT physics floor: pairs within
    rpz + tlookahead * (gs_i + gs_j)  (the exact conservative bound)
  * per-row-block candidate counts (distribution) to size capacities.

Pure NumPy on host — no TPU, no jit.  Run: python scripts/analyze_pairspace.py [N]
"""
import sys

import numpy as np

sys.path.insert(0, ".")

NM = 1852.0
RPZ = 5 * NM
TLOOK = 300.0


def make_geometry(n, geometry="continental", seed=0):
    rng = np.random.default_rng(seed)
    if geometry == "global":
        lat = np.degrees(np.arcsin(rng.uniform(-0.94, 0.94, n)))
        lon = rng.uniform(-180.0, 180.0, n)
    elif geometry == "continental":
        lat = rng.uniform(35.0, 60.0, n)
        lon = rng.uniform(-10.0, 30.0, n)
    else:
        ang = rng.uniform(0, 2 * np.pi, n)
        r = 3.8 * np.sqrt(rng.random(n))
        lat = 52.6 + r * np.cos(ang)
        lon = 5.4 + r * np.sin(ang) / 0.6
    # TAS 130-240 like bench -> gs the same (no wind)
    gs = rng.uniform(130.0, 240.0, n)
    return lat, lon, gs


def morton_perm(lat, lon):
    qlat = np.clip((lat + 90.0) / 180.0 * 32767.0, 0, 32767).astype(np.uint64)
    qlon = np.clip((lon + 180.0) / 360.0 * 32767.0, 0, 32767).astype(np.uint64)

    def spread(x):
        x = (x | (x << 8)) & 0x00FF00FF
        x = (x | (x << 4)) & 0x0F0F0F0F
        x = (x | (x << 2)) & 0x33333333
        x = (x | (x << 1)) & 0x55555555
        return x

    return np.argsort(spread(qlat) | (spread(qlon) << 1), kind="stable")


def stripe_perm(lat, lon, stripe_deg):
    """Lat-stripe-major, lon-within-stripe ordering."""
    s = np.floor((lat - lat.min()) / stripe_deg).astype(np.int64)
    return np.lexsort((lon, s)), s


def box_gap_m(latmin_r, latmax_r, lonmin_r, lonmax_r,
              latmin_c, latmax_c, lonmin_c, lonmax_c):
    """Conservative box-to-box distance lower bound (same family as
    cd_tiled.block_reachability)."""
    dlat = np.maximum(0.0, np.maximum(latmin_r[:, None] - latmax_c[None, :],
                                      latmin_c[None, :] - latmax_r[:, None]))
    dlon = np.maximum(0.0, np.maximum(lonmin_r[:, None] - lonmax_c[None, :],
                                      lonmin_c[None, :] - lonmax_r[:, None]))
    maxabs = np.maximum(
        np.maximum(np.abs(latmin_r), np.abs(latmax_r))[:, None],
        np.maximum(np.abs(latmin_c), np.abs(latmax_c))[None, :])
    cos_lb = np.cos(np.radians(np.minimum(90.0, maxabs)))
    zonal = 2 * 6335000.0 * np.arcsin(
        np.clip(cos_lb * np.sin(np.radians(0.5 * np.minimum(dlon, 360.0))),
                0, 1))
    return np.maximum(dlat * 110000.0, zonal)


def block_boxes(lat, lon, gs, block):
    n = len(lat)
    nb = -(-n // block)
    npad = nb * block - n
    pad = lambda a, v: np.concatenate([a, np.full(npad, v)])
    sh = (nb, block)
    blat = pad(lat, np.nan).reshape(sh)
    blon = pad(lon, np.nan).reshape(sh)
    bgs = pad(gs, 0.0).reshape(sh)
    return (np.nanmin(blat, 1), np.nanmax(blat, 1),
            np.nanmin(blon, 1), np.nanmax(blon, 1), np.nanmax(bgs, 1), nb)


def physics_floor(lat, lon, gs, sample=4000, seed=1):
    """Per-aircraft conservative candidate count, estimated on a sample."""
    n = len(lat)
    rng = np.random.default_rng(seed)
    idx = rng.choice(n, size=min(sample, n), replace=False)
    # local-ENU approximate distances (fine at continental scale for stats)
    counts = np.empty(len(idx))
    for k, i in enumerate(idx):
        dy = (lat - lat[i]) * 111000.0
        dx = (lon - lon[i]) * 111000.0 * np.cos(np.radians(lat[i]))
        d = np.hypot(dx, dy)
        thresh = RPZ + TLOOK * (gs + gs[i])
        counts[k] = np.sum(d <= thresh) - 1
    return counts


def main(n=100_000, geometry="continental"):
    lat, lon, gs = make_geometry(n, geometry)
    print(f"N={n} {geometry}: brute pairs {n*n:.3e}")

    counts = physics_floor(lat, lon, gs)
    floor = counts.mean() * n
    print(f"physics floor (exact conservative bound): "
          f"mean cand/ac {counts.mean():.0f} p99 {np.percentile(counts,99):.0f}"
          f" -> total pairs {floor:.3e}  ({n*n/floor:.0f}x below brute)")

    for block in (256, 128):
        p = morton_perm(lat, lon)
        la, lo, g = lat[p], lon[p], gs[p]
        lmn, lmx, omn, omx, gmx, nb = block_boxes(la, lo, g, block)
        gap = box_gap_m(lmn, lmx, omn, omx, lmn, lmx, omn, omx)
        thresh = RPZ + TLOOK * (gmx[:, None] + gmx[None, :])
        reach = gap <= thresh * 1.05
        pairs = reach.sum() * block * block
        print(f"Morton block={block}: {nb} blocks, reach frac "
              f"{reach.mean():.3f}, pairs {pairs:.3e} "
              f"({pairs/floor:.1f}x floor)")

        # sub-block candidate granularity (mixed-mode scheduler)
        for sub in (32,):
            smn, smx, son, sox, sgx, nsb = block_boxes(la, lo, g, sub)
            gap2 = box_gap_m(lmn, lmx, omn, omx, smn, smx, son, sox)
            th2 = RPZ + TLOOK * (gmx[:, None] + sgx[None, :])
            m = gap2 <= th2 * 1.05
            cand = m.sum(1) * sub          # candidate AC per row block
            pairs2 = (cand * block).sum()
            print(f"  Morton cand sub={sub}: mean cand/blk {cand.mean():.0f} "
                  f"p99 {np.percentile(cand,99):.0f} max {cand.max()} "
                  f"pairs {pairs2:.3e} ({pairs2/floor:.1f}x floor)")

    # Stripe sort: stripes ~ reach radius tall; lon-sorted within
    for stripe_deg in (1.5, 2.0):
        for block in (256, 128):
            p, s = stripe_perm(lat, lon, stripe_deg)
            la, lo, g = lat[p], lon[p], gs[p]
            lmn, lmx, omn, omx, gmx, nb = block_boxes(la, lo, g, block)
            for sub in (32,):
                smn, smx, son, sox, sgx, nsb = block_boxes(la, lo, g, sub)
                gap2 = box_gap_m(lmn, lmx, omn, omx, smn, smx, son, sox)
                th2 = RPZ + TLOOK * (gmx[:, None] + sgx[None, :])
                m = gap2 <= th2 * 1.05
                cand = m.sum(1) * sub
                pairs2 = (cand * block).sum()
                # contiguity: how many contiguous runs of candidate
                # sub-blocks per row (DMA-friendliness)
                runs = np.array([
                    int(np.sum(np.diff(np.flatnonzero(r)) > 1) + 1)
                    if r.any() else 0 for r in m])
                print(f"stripe={stripe_deg} block={block} sub={sub}: "
                      f"mean cand/blk {cand.mean():.0f} "
                      f"p99 {np.percentile(cand,99):.0f} max {cand.max()} "
                      f"pairs {pairs2:.3e} ({pairs2/floor:.1f}x floor) "
                      f"runs mean {runs.mean():.1f} max {runs.max()}")


if __name__ == "__main__":
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    geom = sys.argv[2] if len(sys.argv) > 2 else "continental"
    main(n, geom)


def runs_analysis(n=100_000, geometry="continental"):
    """Block-granular reachability runs: how many contiguous (start,len)
    segments per row block, and the padded pair count after capping the
    segment count by gap-merging (merging only ADDS tiles - stays exact)."""
    lat, lon, gs = make_geometry(n, geometry)
    for name in ("morton", "stripe1.5"):
        if name == "morton":
            p = morton_perm(lat, lon)
        else:
            p, _ = stripe_perm(lat, lon, 1.5)
        la, lo, g = lat[p], lon[p], gs[p]
        for block in (256, 128):
            lmn, lmx, omn, omx, gmx, nb = block_boxes(la, lo, g, block)
            gap = box_gap_m(lmn, lmx, omn, omx, lmn, lmx, omn, omx)
            thresh = RPZ + TLOOK * (gmx[:, None] + gmx[None, :])
            reach = gap <= thresh * 1.05
            nruns, merged_pairs = [], {}
            for cap in (4, 6, 8):
                merged_pairs[cap] = 0
            widths = []
            for i in range(nb):
                r = reach[i]
                j = np.flatnonzero(r)
                if len(j) == 0:
                    nruns.append(0)
                    continue
                # contiguous runs
                splits = np.flatnonzero(np.diff(j) > 1)
                starts = np.concatenate([[j[0]], j[splits + 1]])
                ends = np.concatenate([j[splits], [j[-1]]])  # inclusive
                nruns.append(len(starts))
                widths.append((ends - starts + 1).max())
                for cap in (4, 6, 8):
                    s, e = list(starts), list(ends)
                    while len(s) > cap:
                        gaps = np.array(s[1:]) - np.array(e[:-1])
                        k = int(np.argmin(gaps))
                        e[k] = e[k + 1]
                        del s[k + 1], e[k + 1]
                    merged_pairs[cap] += sum(
                        (ee - ss + 1) for ss, ee in zip(s, e)) * block * block
            nruns = np.array(nruns)
            print(f"{name} block={block}: runs mean {nruns.mean():.1f} "
                  f"p99 {np.percentile(nruns,99):.0f} max {nruns.max()}; "
                  f"max single-run width {max(widths)}; "
                  + " ".join(f"cap{c}: {merged_pairs[c]:.3e}"
                             for c in (4, 6, 8)))


if __name__ == "__main__" and "--runs" in sys.argv:
    runs_analysis(int(sys.argv[1]) if sys.argv[1:2] and
                  sys.argv[1].isdigit() else 100_000)
