"""Merge flight-recorder dumps (obs/trace.py) into one Perfetto trace
and print a per-chunk breakdown table.

Each process dumps its own ring (TRACE DUMP on the sim, the b"TRACE"
event on the server, auto-dumps on guard/mesh trips) as a separate
``trace-<proc>-<pid>-<NNN>-<reason>.json`` file.  All events carry wall
timestamps from a shared epoch anchor (time.time() - perf_counter() at
import), so dumps from processes on ONE host line up on the same axis
and can simply be concatenated; the pid field keeps the tracks apart in
the Perfetto UI.

Run:
    python scripts/trace_report.py trace-*.json [-o merged.json]

The breakdown table groups "X" (complete) events by (pid, seq) — the
host-side chunk sequence number stamped at dispatch — and shows, per
chunk, the dispatch span, the edge-retire span and the reported device
pull latency, plus any instants (guard trips, voided chunks,
mesh_lost/resharded) that share the correlation id.
"""
import argparse
import json
import sys
from collections import defaultdict


def load(paths):
    """Read + concatenate dumps, deduping events that appear in more
    than one (a dump does not clear the ring, so an incident auto-dump
    and a later manual dump from the same process overlap)."""
    events, seen = [], set()
    for p in paths:
        try:
            with open(p) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"skipping {p}: {e}", file=sys.stderr)
            continue
        evs = doc.get("traceEvents", []) if isinstance(doc, dict) else doc
        for ev in evs:
            if not (isinstance(ev, dict) and "ts" in ev):
                continue
            key = (ev.get("pid"), ev.get("tid"), ev["ts"],
                   ev.get("name"), ev.get("ph"))
            if key in seen:
                continue
            seen.add(key)
            events.append(ev)
    events.sort(key=lambda e: e["ts"])
    return events


def merge(events, meta=None):
    doc = {"traceEvents": events, "displayTimeUnit": "ms"}
    if meta:
        doc["metadata"] = meta
    return doc


def chunk_table(events):
    """Rows keyed by (pid, seq): per-chunk span durations + instants."""
    rows = defaultdict(dict)
    loose = []                      # instants with no seq tag
    for ev in events:
        args = ev.get("args") or {}
        seq = args.get("seq")
        if seq is None:
            if ev.get("ph") == "i":
                loose.append(ev)
            continue
        row = rows[(ev.get("pid", 0), seq)]
        row.setdefault("t0", ev["ts"])
        row.setdefault("chunk", args.get("chunk"))
        row.setdefault("world", args.get("world"))
        name = ev.get("name", "?")
        if ev.get("ph") == "X":
            row[name] = ev.get("dur", 0) / 1000.0       # us -> ms
            if name == "chunk_edge" and "latency_ms" in args:
                row["latency_ms"] = args["latency_ms"]
        else:                                           # instant
            row.setdefault("events", []).append(name)
    return rows, loose


def fmt_ms(v):
    return f"{v:8.2f}" if isinstance(v, (int, float)) else " " * 8


def print_table(rows, loose, out=sys.stdout):
    cols = ("dispatch", "edge", "meshchk", "latency")
    head = (f"{'pid':>7} {'seq':>5} {'chunk':>6} {'world':>6} "
            + " ".join(f"{c:>8}" for c in cols) + "  events")
    print(head, file=out)
    print("-" * len(head), file=out)
    for (pid, seq), row in sorted(rows.items(),
                                  key=lambda kv: kv[1].get("t0", 0)):
        world = row.get("world")
        print(f"{pid:>7} {seq:>5} {str(row.get('chunk', '')):>6} "
              f"{('' if world is None else str(world)):>6} "
              f"{fmt_ms(row.get('chunk_dispatch'))} "
              f"{fmt_ms(row.get('chunk_edge'))} "
              f"{fmt_ms(row.get('mesh_check'))} "
              f"{fmt_ms(row.get('latency_ms'))}  "
              f"{','.join(row.get('events', []))}", file=out)
    if loose:
        print("\nuntagged instants:", file=out)
        for ev in loose:
            args = ev.get("args") or {}
            tag = " ".join(f"{k}={v}" for k, v in sorted(args.items()))
            print(f"  {ev['ts']/1e6:12.3f}s pid={ev.get('pid', '?')} "
                  f"{ev.get('name', '?')} {tag}", file=out)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("dumps", nargs="+", help="trace-*.json dump files")
    ap.add_argument("-o", "--out", default=None,
                    help="write the merged Perfetto trace here")
    args = ap.parse_args(argv)

    events = load(args.dumps)
    if not events:
        print("no events found", file=sys.stderr)
        return 1
    if args.out:
        with open(args.out, "w") as f:
            json.dump(merge(events, {"sources": args.dumps}), f)
        print(f"merged {len(events)} events from {len(args.dumps)} "
              f"dump(s) -> {args.out}")

    rows, loose = chunk_table(events)
    print_table(rows, loose)
    return 0


if __name__ == "__main__":
    sys.exit(main())
