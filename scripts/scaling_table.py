"""Measure the sparse CD schedule's multi-chip work division.

Builds the ACTUAL round-4 sharded schedule (stripe sort ->
block_reachability -> build_windows -> contiguous row-slice per device,
exactly what `ops/cd_sched.detect_resolve_sched(mesh=...)` executes) for
the benchmark geometries at N=100k, and reports per-device scheduled
pair counts for mesh sizes 1..32 — the quantity that sets each chip's
kernel time, since the pair math is >60% of the interval and scales
linearly in scheduled pairs (measured ~108 ps/pair on v5e, see
docs/PERF_ANALYSIS.md).

This is schedule-measured on the real layout (not a model): imbalance
shown here is imbalance the chips would see.  What it does NOT measure
is the ICI all-gather of the replicated column slabs (reported as bytes
per interval below) and XLA's collective overlap — one chip cannot
measure those.

Run: PYTHONPATH=. JAX_PLATFORMS=cpu python scripts/scaling_table.py
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_platforms", "cpu")

from bluesky_tpu.ops import cd_sched
from bluesky_tpu.ops.cd_tiled import block_reachability

NM = 1852.0
RPZ, TLOOK = 5 * NM, 300.0
BLOCK, EXTRA, S_CAP, WMAX = 256, 32, 6, 16


def make_fleet(n, geom, seed=0):
    rng = np.random.default_rng(seed)
    if geom == "continental":
        lat = rng.uniform(35.0, 60.0, n)
        lon = rng.uniform(-10.0, 30.0, n)
    elif geom == "global":
        lat = np.degrees(np.arcsin(rng.uniform(-0.94, 0.94, n)))
        lon = rng.uniform(-180.0, 180.0, n)
    else:  # regional: the reference's 230 nm circle
        ang = rng.uniform(0, 2 * np.pi, n)
        r = 3.8 * np.sqrt(rng.random(n))
        lat = 52.6 + r * np.cos(ang)
        lon = 5.4 + r * np.sin(ang) / 0.6
    gs = rng.uniform(130.0, 240.0, n)
    alt = rng.uniform(3000.0, 11000.0, n)
    vs = rng.uniform(-15.0, 15.0, n)
    return (jnp.asarray(lat, jnp.float32), jnp.asarray(lon, jnp.float32),
            jnp.asarray(gs, jnp.float32), jnp.asarray(alt, jnp.float32),
            jnp.asarray(vs, jnp.float32))


def schedule_pairs_per_row(lat, lon, gs, alt, vs):
    """[nb] scheduled block-granular pairs per row block, via the real
    round-4 schedule (windows for covered rows, row-restricted full
    grid for overflow rows)."""
    n = lat.shape[0]
    active = jnp.ones((n,), bool)
    thresh = cd_sched.reach_threshold_m(gs, active, TLOOK, RPZ)
    dest = cd_sched.stripe_sort_dest(lat, lon, gs, active, thresh,
                                     BLOCK, EXTRA, alt=alt, vs=vs)
    nb = -(-n // BLOCK) + EXTRA
    n_tot = nb * BLOCK
    plat, plon, pgs, palt, pvs, pact = cd_sched.scatter_padded(
        [lat, lon, gs, alt, vs, active.astype(jnp.float32)], dest, n_tot)
    reach = block_reachability(plat, plon, pgs, pact > 0.5, nb, BLOCK,
                               RPZ, TLOOK, alt=palt, vs=pvs,
                               hpz=1000 * 0.3048)
    st, ln, overflow = cd_sched.build_windows(reach, S_CAP, WMAX,
                                              pad_start=nb)
    win_pairs = jnp.sum(ln, axis=1) * BLOCK * BLOCK
    grid_pairs = jnp.sum(reach, axis=1) * BLOCK * BLOCK
    per_row = jnp.where(overflow, grid_pairs, win_pairs)
    return np.asarray(per_row), nb, int(jnp.sum(overflow))


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    ps_per_pair = 108e-12          # measured v5e pair cost (PERF_ANALYSIS)
    print(f"N = {n}; block {BLOCK}, s_cap {S_CAP}, wmax {WMAX}; "
          f"pair cost {ps_per_pair*1e12:.0f} ps (measured)")
    for geom in ("continental", "global", "regional"):
        per_row, nb, n_over = schedule_pairs_per_row(
            *make_fleet(n, geom))
        total = per_row.sum()
        # Replicated column slabs: [nb+wmax, 16, block] f32 per interval
        ag_mb = (nb + WMAX) * 16 * BLOCK * 4 / 1e6
        print(f"\n[{geom}] rows={nb} overflow_rows={n_over} "
              f"total scheduled pairs={total:.3e} "
              f"column all-gather={ag_mb:.1f} MB/interval")
        print(f"{'D':>3} {'rows/dev':>8} {'max pairs/dev':>14} "
              f"{'mean pairs/dev':>14} {'imbalance':>9} "
              f"{'kernel ms/dev':>13}")
        for d in (1, 2, 4, 8, 16, 32):
            nbp = -(-nb // d) * d
            rows = np.pad(per_row, (0, nbp - nb))
            # the INTERLEAVED assignment detect_resolve_sched uses
            # (device d owns rows d, d+D, ...)
            dev = rows.reshape(nbp // d, d).T.sum(axis=1)
            mx, mean = dev.max(), dev.mean()
            print(f"{d:>3} {nbp//d:>8} {mx:>14.3e} {mean:>14.3e} "
                  f"{mx/max(mean,1):>9.2f} {mx*ps_per_pair*1e3:>13.2f}")


if __name__ == "__main__":
    main()
