"""Measure the sparse CD schedule's multi-chip work division.

Builds the ACTUAL round-4 sharded schedule (stripe sort ->
block_reachability -> build_windows -> contiguous row-slice per device,
exactly what `ops/cd_sched.detect_resolve_sched(mesh=...)` executes) for
the benchmark geometries at N=100k, and reports per-device scheduled
pair counts for mesh sizes 1..32 — the quantity that sets each chip's
kernel time, since the pair math is >60% of the interval and scales
linearly in scheduled pairs (measured ~108 ps/pair on v5e, see
docs/PERF_ANALYSIS.md).

This is schedule-measured on the real layout (not a model): imbalance
shown here is imbalance the chips would see.  What it does NOT measure
is the ICI all-gather of the replicated column slabs (reported as bytes
per interval below) and XLA's collective overlap — one chip cannot
measure those.

Run: PYTHONPATH=. JAX_PLATFORMS=cpu python scripts/scaling_table.py
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_platforms", "cpu")

from bluesky_tpu.ops import cd_sched
from bluesky_tpu.ops.cd_tiled import block_reachability

NM = 1852.0
RPZ, TLOOK = 5 * NM, 300.0
BLOCK, EXTRA, S_CAP, WMAX = 256, 32, 6, 16


def make_fleet(n, geom, seed=0):
    rng = np.random.default_rng(seed)
    if geom == "continental":
        lat = rng.uniform(35.0, 60.0, n)
        lon = rng.uniform(-10.0, 30.0, n)
    elif geom == "global":
        lat = np.degrees(np.arcsin(rng.uniform(-0.94, 0.94, n)))
        lon = rng.uniform(-180.0, 180.0, n)
    else:  # regional: the reference's 230 nm circle
        ang = rng.uniform(0, 2 * np.pi, n)
        r = 3.8 * np.sqrt(rng.random(n))
        lat = 52.6 + r * np.cos(ang)
        lon = 5.4 + r * np.sin(ang) / 0.6
    gs = rng.uniform(130.0, 240.0, n)
    alt = rng.uniform(3000.0, 11000.0, n)
    vs = rng.uniform(-15.0, 15.0, n)
    return (jnp.asarray(lat, jnp.float32), jnp.asarray(lon, jnp.float32),
            jnp.asarray(gs, jnp.float32), jnp.asarray(alt, jnp.float32),
            jnp.asarray(vs, jnp.float32))


def schedule_pairs_per_row(lat, lon, gs, alt, vs, extra=EXTRA,
                           spread_pad=False):
    """[nb] scheduled block-granular pairs per row block, via the real
    round-4 schedule (windows for covered rows, row-restricted full
    grid for overflow rows).  ``extra``/``spread_pad`` select the
    SPATIAL layout variant (device-divisible padding, count-diluted)."""
    n = lat.shape[0]
    active = jnp.ones((n,), bool)
    thresh = cd_sched.reach_threshold_m(gs, active, TLOOK, RPZ)
    dest = cd_sched.stripe_sort_dest(lat, lon, gs, active, thresh,
                                     BLOCK, extra, alt=alt, vs=vs,
                                     spread_pad=spread_pad)
    nb = -(-n // BLOCK) + extra
    n_tot = nb * BLOCK
    plat, plon, pgs, palt, pvs, pact = cd_sched.scatter_padded(
        [lat, lon, gs, alt, vs, active.astype(jnp.float32)], dest, n_tot)
    reach = block_reachability(plat, plon, pgs, pact > 0.5, nb, BLOCK,
                               RPZ, TLOOK, alt=palt, vs=pvs,
                               hpz=1000 * 0.3048)
    st, ln, overflow = cd_sched.build_windows(reach, S_CAP, WMAX,
                                              pad_start=nb)
    win_pairs = jnp.sum(ln, axis=1) * BLOCK * BLOCK
    grid_pairs = jnp.sum(reach, axis=1) * BLOCK * BLOCK
    per_row = jnp.where(overflow, grid_pairs, win_pairs)
    return np.asarray(per_row), nb, int(jnp.sum(overflow)), dest, \
        np.asarray(reach)


def spatial_stats(lat, lon, gs, alt, vs, ndev, halo_blocks=0):
    """Measured per-device division of the SPATIAL decomposition at
    D=ndev: scheduled pairs per device (contiguous stripe split on the
    count-diluted layout), aircraft occupancy per device, the widest
    halo the reachability actually needs, and the halo exchange volume
    per device per interval.  This is schedule-measured on the real
    layout, like the replicate columns — what one chip cannot measure
    is the ICI time itself."""
    n = lat.shape[0]
    extra, nb, nb_l, n_tot = cd_sched.spatial_layout(n, BLOCK, ndev)
    per_row, nb2, n_over, dest, reach = schedule_pairs_per_row(
        lat, lon, gs, alt, vs, extra=extra, spread_pad=True)
    assert nb2 == nb
    dev_pairs = per_row.reshape(ndev, nb_l).sum(axis=1)
    dest_np = np.asarray(dest)
    S = nb_l * BLOCK
    counts = np.bincount(np.minimum(dest_np // S, ndev - 1),
                         minlength=ndev)
    # widest halo the reachability needs (blocks past the owning
    # device's range over reachable pairs) -> the halo the refresh
    # would demand; the multi-hop exchange supports any width
    bi = np.arange(nb)
    d_i = bi // nb_l
    need = np.maximum(np.maximum(
        (d_i * nb_l)[:, None] - bi[None, :],
        bi[None, :] - ((d_i + 1) * nb_l)[:, None] + 1), 0)
    halo_need = int(need[reach].max()) if reach.any() else 0
    halo = halo_blocks or max(nb_l, halo_need)
    # exchanged boundary slabs: 2 directions x halo blocks x 16 rows
    halo_bytes_dev = 2 * halo * 16 * BLOCK * 4
    # summary metadata all-gather: 8 f32 vectors of nb entries
    summ_bytes = 8 * nb * 4
    return dict(ndev=ndev, extra=extra, nb=nb, nb_local=nb_l,
                dev_pairs=dev_pairs, counts=counts,
                overflow_rows=n_over, halo_blocks=halo,
                halo_need=halo_need,
                halo_bytes_dev=halo_bytes_dev, summ_bytes=summ_bytes)


def main():
    import bench
    out = bench.pop_out_flag(sys.argv, None)   # e.g. BENCH_SCALING.json
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    ps_per_pair = 108e-12          # measured v5e pair cost (PERF_ANALYSIS)
    print(f"N = {n}; block {BLOCK}, s_cap {S_CAP}, wmax {WMAX}; "
          f"pair cost {ps_per_pair*1e12:.0f} ps (measured)")
    out_rows = []

    def record(geom, d, mode, mx, mean, wire_mb, occ):
        out_rows.append({
            "n": n, "geometry": geom, "D": d, "mode": mode,
            "max_pairs_dev": float(mx), "mean_pairs_dev": float(mean),
            "imbalance": round(float(mx / max(mean, 1)), 3),
            "kernel_ms_dev": round(float(mx * ps_per_pair * 1e3), 3),
            "wire_mb_dev": round(float(wire_mb), 3),
            "occ": None if occ is None else round(float(occ), 3),
            "protocol": ("schedule-measured on the real round-4 "
                         "layout; kernel ms from the measured "
                         f"{ps_per_pair*1e12:.0f} ps/pair v5e cost"),
        })

    for geom in ("continental", "global", "regional"):
        fleet = make_fleet(n, geom)
        per_row, nb, n_over, _, _ = schedule_pairs_per_row(*fleet)
        total = per_row.sum()
        # Replicated mode wire: the O(N) raw column gathers (~90 B/ac,
        # HLO-verified — XLA regathers columns, not the slab array)
        repl_mb = 90.0 * n / 1e6
        print(f"\n[{geom}] rows={nb} overflow_rows={n_over} "
              f"total scheduled pairs={total:.3e} "
              f"replicate-mode column gathers={repl_mb:.1f} MB/interval")
        print(f"{'D':>3} {'mode':>9} {'max pairs/dev':>14} "
              f"{'mean pairs/dev':>14} {'imbalance':>9} "
              f"{'kernel ms/dev':>13} {'wire MB/dev':>11} "
              f"{'occ':>5}")
        for d in (1, 2, 4, 8, 16, 32):
            nbp = -(-nb // d) * d
            rows = np.pad(per_row, (0, nbp - nb))
            # REPLICATE: the INTERLEAVED assignment (device d owns rows
            # d, d+D, ...) against replicated O(N) columns
            dev = rows.reshape(nbp // d, d).T.sum(axis=1)
            mx, mean = dev.max(), dev.mean()
            print(f"{d:>3} {'replicate':>9} {mx:>14.3e} {mean:>14.3e} "
                  f"{mx/max(mean,1):>9.2f} {mx*ps_per_pair*1e3:>13.2f} "
                  f"{0.0 if d == 1 else repl_mb:>11.2f} {'-':>5}")
            record(geom, d, "replicate", mx, mean,
                   0.0 if d == 1 else repl_mb, None)
            if d == 1:
                continue
            # SPATIAL: contiguous stripe ownership on the
            # count-diluted device-divisible layout, halo exchange only
            st = spatial_stats(*fleet, ndev=d)
            smx, smean = st["dev_pairs"].max(), st["dev_pairs"].mean()
            wire_mb = (st["halo_bytes_dev"] + st["summ_bytes"]) / 1e6
            occ = st["counts"].max() / (n / d)
            print(f"{d:>3} {'spatial':>9} {smx:>14.3e} {smean:>14.3e} "
                  f"{smx/max(smean,1):>9.2f} "
                  f"{smx*ps_per_pair*1e3:>13.2f} {wire_mb:>11.2f} "
                  f"{occ:>5.2f}")
            record(geom, d, "spatial", smx, smean, wire_mb, occ)
    if out:
        # shared writer: platform tag + BENCH_HISTORY series so the
        # perf sentinel watches schedule balance like any other bench
        bench.write_bench_json(out, out_rows)


if __name__ == "__main__":
    main()
