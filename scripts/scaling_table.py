"""Measure the sparse CD schedule's multi-chip work division.

Builds the ACTUAL round-4 sharded schedule (stripe sort ->
block_reachability -> build_windows -> contiguous row-slice per device,
exactly what `ops/cd_sched.detect_resolve_sched(mesh=...)` executes) for
the benchmark geometries at N=100k, and reports per-device scheduled
pair counts for mesh sizes 1..32 — the quantity that sets each chip's
kernel time, since the pair math is >60% of the interval and scales
linearly in scheduled pairs (measured ~108 ps/pair on v5e, see
docs/PERF_ANALYSIS.md).

This is schedule-measured on the real layout (not a model): imbalance
shown here is imbalance the chips would see.  What it does NOT measure
is the ICI all-gather of the replicated column slabs (reported as bytes
per interval below) and XLA's collective overlap — one chip cannot
measure those.

Run: PYTHONPATH=. JAX_PLATFORMS=cpu python scripts/scaling_table.py
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_platforms", "cpu")

from bluesky_tpu.ops import cd_sched
from bluesky_tpu.ops.cd_tiled import block_reachability

NM = 1852.0
RPZ, TLOOK = 5 * NM, 300.0
BLOCK, EXTRA, S_CAP, WMAX = 256, 32, 6, 16


def make_fleet(n, geom, seed=0):
    rng = np.random.default_rng(seed)
    if geom == "continental":
        lat = rng.uniform(35.0, 60.0, n)
        lon = rng.uniform(-10.0, 30.0, n)
    elif geom == "global":
        lat = np.degrees(np.arcsin(rng.uniform(-0.94, 0.94, n)))
        lon = rng.uniform(-180.0, 180.0, n)
    else:  # regional: the reference's 230 nm circle
        ang = rng.uniform(0, 2 * np.pi, n)
        r = 3.8 * np.sqrt(rng.random(n))
        lat = 52.6 + r * np.cos(ang)
        lon = 5.4 + r * np.sin(ang) / 0.6
    gs = rng.uniform(130.0, 240.0, n)
    alt = rng.uniform(3000.0, 11000.0, n)
    vs = rng.uniform(-15.0, 15.0, n)
    return (jnp.asarray(lat, jnp.float32), jnp.asarray(lon, jnp.float32),
            jnp.asarray(gs, jnp.float32), jnp.asarray(alt, jnp.float32),
            jnp.asarray(vs, jnp.float32))


def schedule_pairs_per_row(lat, lon, gs, alt, vs, extra=EXTRA,
                           spread_pad=False):
    """[nb] scheduled block-granular pairs per row block, via the real
    round-4 schedule (windows for covered rows, row-restricted full
    grid for overflow rows).  ``extra``/``spread_pad`` select the
    SPATIAL layout variant (device-divisible padding, count-diluted)."""
    n = lat.shape[0]
    active = jnp.ones((n,), bool)
    thresh = cd_sched.reach_threshold_m(gs, active, TLOOK, RPZ)
    dest = cd_sched.stripe_sort_dest(lat, lon, gs, active, thresh,
                                     BLOCK, extra, alt=alt, vs=vs,
                                     spread_pad=spread_pad)
    nb = -(-n // BLOCK) + extra
    n_tot = nb * BLOCK
    plat, plon, pgs, palt, pvs, pact = cd_sched.scatter_padded(
        [lat, lon, gs, alt, vs, active.astype(jnp.float32)], dest, n_tot)
    reach = block_reachability(plat, plon, pgs, pact > 0.5, nb, BLOCK,
                               RPZ, TLOOK, alt=palt, vs=pvs,
                               hpz=1000 * 0.3048)
    st, ln, overflow = cd_sched.build_windows(reach, S_CAP, WMAX,
                                              pad_start=nb)
    win_pairs = jnp.sum(ln, axis=1) * BLOCK * BLOCK
    grid_pairs = jnp.sum(reach, axis=1) * BLOCK * BLOCK
    per_row = jnp.where(overflow, grid_pairs, win_pairs)
    return np.asarray(per_row), nb, int(jnp.sum(overflow)), dest, \
        np.asarray(reach)


def _pairs_for_dest(arrs, dest, nb):
    """Scheduled pairs per row block + reach matrix for an already
    computed sort destination (shared by the stripe and tile stats)."""
    lat, lon, gs, alt, vs, active = arrs
    n_tot = nb * BLOCK
    plat, plon, pgs, palt, pvs, pact = cd_sched.scatter_padded(
        [lat, lon, gs, alt, vs, active.astype(jnp.float32)], dest, n_tot)
    reach = block_reachability(plat, plon, pgs, pact > 0.5, nb, BLOCK,
                               RPZ, TLOOK, alt=palt, vs=pvs,
                               hpz=1000 * 0.3048)
    st, ln, overflow = cd_sched.build_windows(reach, S_CAP, WMAX,
                                              pad_start=nb)
    win_pairs = jnp.sum(ln, axis=1) * BLOCK * BLOCK
    grid_pairs = jnp.sum(reach, axis=1) * BLOCK * BLOCK
    per_row = jnp.where(overflow, grid_pairs, win_pairs)
    return (np.asarray(per_row), int(jnp.sum(overflow)),
            np.asarray(reach))


def near_square_tiles(ndev):
    """R x C factorisation of ndev with R >= C, C as close to sqrt as
    divides (8 -> 4x2, 16 -> 4x4, prime -> p x 1) — mirrors the
    Simulation default for SHARD TILE without a shape argument."""
    c = int(np.sqrt(ndev))
    while c > 1 and ndev % c:
        c -= 1
    c = max(c, 1)
    return (ndev // c, c)


def tile_stats(lat, lon, gs, alt, vs, tiles, budgets=()):
    """Measured per-tile division of the 2-D TILES decomposition on the
    R x C lat x lon mesh: scheduled pairs per tile (contiguous slot
    range split on the count-proportional tile layout), aircraft
    occupancy per tile, the per-offset halo the reachability actually
    needs (edge AND corner neighbours, lon-wrap deduped), and the halo
    exchange volume per device per interval.  Halo wire scales with the
    tile PERIMETER (a few blocks per canonical offset) instead of the
    full stripe width — that is the point of the 2-D decomposition.
    ``uncovered`` counts reachable block pairs OUTSIDE the neighbour
    set; nonzero means the one-tile halo cannot cover the reach and the
    refresh would refuse (guard bit 2) rather than silently miss."""
    R, C = int(tiles[0]), int(tiles[1])
    ndev = R * C
    n = lat.shape[0]
    extra, nb, nb_t, n_tot = cd_sched.spatial_layout(n, BLOCK, ndev)
    active = jnp.ones((n,), bool)
    thresh = cd_sched.reach_threshold_m(gs, active, TLOOK, RPZ)
    dest = cd_sched.tile_sort_dest(lat, lon, gs, active, thresh, BLOCK,
                                   extra, tiles, alt=alt, vs=vs)
    per_row, n_over, reach = _pairs_for_dest(
        (lat, lon, gs, alt, vs, active), dest, nb)
    dev_pairs = per_row.reshape(ndev, nb_t).sum(axis=1)
    dest_np = np.asarray(dest)
    counts = np.bincount(np.minimum(dest_np // (nb_t * BLOCK), ndev - 1),
                         minlength=ndev)
    offs = cd_sched.tile_offsets(tiles)
    t_of = np.arange(nb) // nb_t                     # owning tile per block
    r_of, c_of = t_of // C, t_of % C
    # per-offset measured need: widest sender-block set any receiver
    # tile reaches at that offset (what the refresh pins budgets from)
    needs = []
    for dr, dcm in offs:
        need = 0
        for rt in range(R):
            for ct in range(C):
                sr, sc = rt + dr, (ct + dcm) % C
                if not 0 <= sr < R:
                    continue
                recv = t_of == rt * C + ct
                send = t_of == sr * C + sc
                need = max(need, int(
                    reach[np.ix_(recv, send)].any(axis=0).sum()))
        needs.append(need)
    # reachable pairs outside {self} + canonical neighbour offsets
    dr_m = r_of[:, None] - r_of[None, :]
    dc_m = (c_of[:, None] - c_of[None, :]) % C
    neigh = (dr_m == 0) & (dc_m == 0)
    for dr, dcm in offs:
        # receiver i reaching sender j at offset (dr, dcm): j's tile is
        # i's tile shifted by the offset, i.e. r_j - r_i == dr (sender
        # minus receiver), dc likewise mod C
        neigh |= ((r_of[None, :] - r_of[:, None]) == dr) & \
                 (((c_of[None, :] - c_of[:, None]) % C) == dcm)
    uncovered = int((reach & ~neigh).sum())
    if not budgets:
        budgets = tuple(int(min(max(4, -(-nd * 5 // 4)), nb_t))
                        for nd in needs)
    wire_blocks = cd_sched.tile_wire_blocks(tiles, budgets, nb_t)
    # each received block: 16-row f32 summary slab + 1 int32 gid row
    halo_bytes_dev = wire_blocks * (16 + 1) * BLOCK * 4
    summ_bytes = 8 * nb * 4
    return dict(ndev=ndev, tiles=(R, C), extra=extra, nb=nb,
                nb_local=nb_t, dev_pairs=dev_pairs, counts=counts,
                overflow_rows=n_over, offsets=offs,
                halo_need=tuple(needs), budgets=budgets,
                wire_blocks=wire_blocks, uncovered=uncovered,
                halo_bytes_dev=halo_bytes_dev, summ_bytes=summ_bytes)


def spatial_stats(lat, lon, gs, alt, vs, ndev, halo_blocks=0):
    """Measured per-device division of the SPATIAL decomposition at
    D=ndev: scheduled pairs per device (contiguous stripe split on the
    count-diluted layout), aircraft occupancy per device, the widest
    halo the reachability actually needs, and the halo exchange volume
    per device per interval.  This is schedule-measured on the real
    layout, like the replicate columns — what one chip cannot measure
    is the ICI time itself."""
    n = lat.shape[0]
    extra, nb, nb_l, n_tot = cd_sched.spatial_layout(n, BLOCK, ndev)
    per_row, nb2, n_over, dest, reach = schedule_pairs_per_row(
        lat, lon, gs, alt, vs, extra=extra, spread_pad=True)
    assert nb2 == nb
    dev_pairs = per_row.reshape(ndev, nb_l).sum(axis=1)
    dest_np = np.asarray(dest)
    S = nb_l * BLOCK
    counts = np.bincount(np.minimum(dest_np // S, ndev - 1),
                         minlength=ndev)
    # widest halo the reachability needs (blocks past the owning
    # device's range over reachable pairs) -> the halo the refresh
    # would demand; the multi-hop exchange supports any width
    bi = np.arange(nb)
    d_i = bi // nb_l
    need = np.maximum(np.maximum(
        (d_i * nb_l)[:, None] - bi[None, :],
        bi[None, :] - ((d_i + 1) * nb_l)[:, None] + 1), 0)
    halo_need = int(need[reach].max()) if reach.any() else 0
    halo = halo_blocks or max(nb_l, halo_need)
    # exchanged boundary slabs: 2 directions x halo blocks x 16 rows
    halo_bytes_dev = 2 * halo * 16 * BLOCK * 4
    # summary metadata all-gather: 8 f32 vectors of nb entries
    summ_bytes = 8 * nb * 4
    return dict(ndev=ndev, extra=extra, nb=nb, nb_local=nb_l,
                dev_pairs=dev_pairs, counts=counts,
                overflow_rows=n_over, halo_blocks=halo,
                halo_need=halo_need,
                halo_bytes_dev=halo_bytes_dev, summ_bytes=summ_bytes)


def main():
    import bench
    out = bench.pop_out_flag(sys.argv, None)   # e.g. BENCH_SCALING.json
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    ps_per_pair = 108e-12          # measured v5e pair cost (PERF_ANALYSIS)
    print(f"N = {n}; block {BLOCK}, s_cap {S_CAP}, wmax {WMAX}; "
          f"pair cost {ps_per_pair*1e12:.0f} ps (measured)")
    out_rows = []

    def record(geom, d, mode, mx, mean, wire_mb, occ, tile_shape=None):
        row = {
            "n": n, "geometry": geom, "D": d, "mode": mode,
            "max_pairs_dev": float(mx), "mean_pairs_dev": float(mean),
            "imbalance": round(float(mx / max(mean, 1)), 3),
            "kernel_ms_dev": round(float(mx * ps_per_pair * 1e3), 3),
            "wire_mb_dev": round(float(wire_mb), 3),
            "occ": None if occ is None else round(float(occ), 3),
            "protocol": ("schedule-measured on the real round-4 "
                         "layout; kernel ms from the measured "
                         f"{ps_per_pair*1e12:.0f} ps/pair v5e cost"),
        }
        if tile_shape:
            row["tile_shape"] = tile_shape
        out_rows.append(row)

    occ_div = {}                       # geom -> (spatial occ, tiles occ)
    for geom in ("continental", "global", "regional"):
        fleet = make_fleet(n, geom)
        per_row, nb, n_over, _, _ = schedule_pairs_per_row(*fleet)
        total = per_row.sum()
        # Replicated mode wire: the O(N) raw column gathers (~90 B/ac,
        # HLO-verified — XLA regathers columns, not the slab array)
        repl_mb = 90.0 * n / 1e6
        print(f"\n[{geom}] rows={nb} overflow_rows={n_over} "
              f"total scheduled pairs={total:.3e} "
              f"replicate-mode column gathers={repl_mb:.1f} MB/interval")
        print(f"{'D':>3} {'mode':>9} {'max pairs/dev':>14} "
              f"{'mean pairs/dev':>14} {'imbalance':>9} "
              f"{'kernel ms/dev':>13} {'wire MB/dev':>11} "
              f"{'occ':>5}")
        for d in (1, 2, 4, 8, 16, 32):
            nbp = -(-nb // d) * d
            rows = np.pad(per_row, (0, nbp - nb))
            # REPLICATE: the INTERLEAVED assignment (device d owns rows
            # d, d+D, ...) against replicated O(N) columns
            dev = rows.reshape(nbp // d, d).T.sum(axis=1)
            mx, mean = dev.max(), dev.mean()
            print(f"{d:>3} {'replicate':>9} {mx:>14.3e} {mean:>14.3e} "
                  f"{mx/max(mean,1):>9.2f} {mx*ps_per_pair*1e3:>13.2f} "
                  f"{0.0 if d == 1 else repl_mb:>11.2f} {'-':>5}")
            record(geom, d, "replicate", mx, mean,
                   0.0 if d == 1 else repl_mb, None)
            if d == 1:
                continue
            # SPATIAL: contiguous stripe ownership on the
            # count-diluted device-divisible layout, halo exchange only
            st = spatial_stats(*fleet, ndev=d)
            smx, smean = st["dev_pairs"].max(), st["dev_pairs"].mean()
            wire_mb = (st["halo_bytes_dev"] + st["summ_bytes"]) / 1e6
            occ = st["counts"].max() / (n / d)
            print(f"{d:>3} {'spatial':>9} {smx:>14.3e} {smean:>14.3e} "
                  f"{smx/max(smean,1):>9.2f} "
                  f"{smx*ps_per_pair*1e3:>13.2f} {wire_mb:>11.2f} "
                  f"{occ:>5.2f}")
            record(geom, d, "spatial", smx, smean, wire_mb, occ)
            # TILES: 2-D lat x lon mesh, contiguous tile ownership,
            # edge+corner halo exchange (wire ~ tile perimeter, not
            # stripe width)
            tiles = near_square_tiles(d)
            if tiles[1] == 1:
                occ_div.setdefault(geom, {})[d] = (occ, None)
                continue               # degenerate 1-D: same as spatial
            ts = tile_stats(*fleet, tiles=tiles)
            tmx, tmean = ts["dev_pairs"].max(), ts["dev_pairs"].mean()
            twire_mb = (ts["halo_bytes_dev"] + ts["summ_bytes"]) / 1e6
            tocc = ts["counts"].max() / (n / d)
            label = f"tile{tiles[0]}x{tiles[1]}"
            print(f"{d:>3} {label:>9} {tmx:>14.3e} {tmean:>14.3e} "
                  f"{tmx/max(tmean,1):>9.2f} "
                  f"{tmx*ps_per_pair*1e3:>13.2f} {twire_mb:>11.2f} "
                  f"{tocc:>5.2f}")
            if ts["uncovered"]:
                print(f"    !! {ts['uncovered']} reachable block pairs "
                      f"outside the 1-tile halo -> refresh would "
                      f"refuse this shape (guard bit 2)")
            record(geom, d, "tiles", tmx, tmean, twire_mb, tocc,
                   tile_shape=f"{tiles[0]}x{tiles[1]}")
            occ_div.setdefault(geom, {})[d] = (occ, tocc)
    # stripe-vs-tile occupancy divergence on the GLOBAL geometry: 1-D
    # latitude stripes get thinner as D grows while the fleet spans the
    # whole sphere (a stripe must still hold its full lon extent), so
    # stripe occupancy drifts from the even split; 2-D tiles keep both
    # cuts count-proportional and stay near 1.0x.
    gdiv = occ_div.get("global", {})
    for d in sorted(gdiv):
        so, to = gdiv[d]
        if to is None:
            continue
        print(f"\n[global] D={d}: stripe occupancy {so:.2f}x even "
              f"split vs tiles {to:.2f}x "
              f"(divergence {so/max(to, 1e-9):.2f}x)")
        record("global", d, "occ_divergence", 0.0, 0.0, 0.0,
               so / max(to, 1e-9),
               tile_shape="x".join(map(str, near_square_tiles(d))))
    if out:
        # shared writer: platform tag + BENCH_HISTORY series so the
        # perf sentinel watches schedule balance like any other bench
        bench.write_bench_json(out, out_rows)


if __name__ == "__main__":
    main()
