"""TRAFGEN-driven density sweep: the reference benchmark config #3.

Spins up circle traffic with the TRAFGEN plugin (12 edge segments, inward
flows) until a target aircraft count is reached, then measures sustained
full-pipeline throughput (FMS + CD&R + perf + kinematics) at that density.

Usage:  python scripts/density_sweep.py [N ...]     (default: 1000 10000)

Prints one JSON line per density with aircraft-steps/s and wall time.
Mirrors BASELINE.md config #3 (plugins/trafgen.py 10k/50k/100k circle
sweep); the spawn phase exercises the batched create path, the measure
phase the scanned step.
"""
import json
import sys
import time

sys.path.insert(0, ".")


def sweep(n_target, spawn_circle_nm=230.0):
    import os

    import jax
    # The axon sitecustomize hook pins jax_platforms to the TPU tunnel
    # before this runs; honour an explicit JAX_PLATFORMS override (e.g.
    # cpu smoke runs of the sweep).
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax.numpy as jnp
    from bluesky_tpu.simulation.sim import Simulation

    nmax = int(n_target * 1.25)
    sim = Simulation(nmax=nmax, dtype=jnp.float32)
    st = sim.stack
    st.stack("PLUGINS LOAD TRAFGEN")
    st.stack(f"TRAFGEN CIRCLE 52.6 5.4 {spawn_circle_nm}")
    # 12 segments, even inbound flows sized to reach n_target quickly
    flow = max(3600.0, n_target * 3600.0 / (12 * 120.0))  # fill in ~2 min
    for brg in range(0, 360, 30):
        st.stack(f"TRAFGEN SRC SEGM{brg} FLOW {flow}")
        st.stack(f"TRAFGEN SRC SEGM{brg} DEST SEGM{(brg + 180) % 360}")
    st.process()
    sim.op()
    sim.fastforward()

    t0 = time.perf_counter()
    while sim.traf.ntraf < n_target:
        sim.step()
        if time.perf_counter() - t0 > 600.0:
            break
    spawn_wall = time.perf_counter() - t0
    n_reached = sim.traf.ntraf

    # Freeze population for the measurement: drop the generator plugin
    # entirely so its 0.1 s hook interval stops clamping the device chunk.
    st.stack("PLUGINS REMOVE TRAFGEN")
    st.process()
    sim.step()

    # Sustained throughput at this density
    nsteps = 0
    simt0 = sim.simt
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < 10.0:
        sim.step()
        nsteps += 1
    wall = time.perf_counter() - t0
    sim_advanced = sim.simt - simt0
    steps = sim_advanced / sim.simdt
    result = {
        "metric": f"density-sweep N={n_reached}",
        "value": round(n_reached * steps / wall, 1),
        "unit": "aircraft-steps/s",
        "n": n_reached,
        "spawn_wall_s": round(spawn_wall, 1),
        "xrealtime": round(sim_advanced / wall, 1),
    }
    print(json.dumps(result))
    return result


if __name__ == "__main__":
    targets = [int(a) for a in sys.argv[1:]] or [1000, 10000]
    for n in targets:
        sweep(n)
