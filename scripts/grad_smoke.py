"""CI perf-smoke: differentiable-simulation contract, cheap enough for
every PR (the perf-smoke lane, .github/workflows/ci.yml).

A tiny 2-aircraft head-on scene is optimized to ZERO hard-metric LoS by
gradient descent on waypoint/time offsets (the ISSUE-7 demo at CI
scale), asserting the three contracts:

1. the objective DECREASES (first -> last iterate);
2. every gradient is finite: the extended guard word stays -1 through
   forward AND backward passes;
3. the hard verification scan (exact step, serving dt) confirms the
   optimized plan: LoS before > 0, after == 0.

Then a micro ``bench.run_grad`` writes BENCH_GRAD.json (uploaded as a
CI artifact) so forward+backward vs forward-only steps/s regressions
show in the job log.  Exits non-zero on any violation.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    import jax
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    from bluesky_tpu.diff import optimize as dopt

    traf, acfg = dopt.conflict_scene(2, dtype=jnp.float64)
    res = dopt.optimize(traf.state, acfg, tend=400.0, simdt=1.0,
                        chunk=50, iters=25)
    print(f"grad-smoke: objective {res.objective[0]:.4f} -> "
          f"{res.objective[-1]:.4f} in {res.iters} iters, "
          f"guard word {res.bad}, hard LoS "
          f"{res.hard_los_before} -> {res.hard_los_after}")
    assert res.bad == -1, \
        f"integrity-guard trip in the forward/backward pass: {res.bad}"
    assert all(g == g and abs(g) != float("inf")
               for g in res.grad_norm), "non-finite gradient norm"
    assert res.objective[-1] < res.objective[0], \
        "objective did not decrease"
    assert res.hard_los_before > 0, \
        "smoke scene lost its conflict (bad baseline)"
    assert res.hard_los_after == 0, \
        f"optimized plan still has {res.hard_los_after} hard LoS"
    print("grad-smoke: optimize-to-zero-LoS OK")

    # micro fwd+bwd vs fwd-only rows -> BENCH_GRAD.json (CI artifact)
    import bench
    out = bench.pop_out_flag(sys.argv, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "BENCH_GRAD.json"))
    rows = bench.run_grad(n_ac=50, tend=200.0, simdt=1.0, chunk=50,
                          reps=1)
    gr = rows[2]
    bench.write_bench_json(out, rows, headline={
        "n": 50, "bwd_over_fwd": gr.get("bwd_over_fwd"),
        "fwd_bwd_ac_steps_per_s": gr["ac_steps_per_s"],
        "note": ("CI smoke numbers (runner-noisy, informational); "
                 "chip rows come from `bench.py --grad` on real "
                 "hardware")})
    print("grad-smoke: PASS")


if __name__ == "__main__":
    main()
