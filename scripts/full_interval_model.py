"""VERDICT r4 #1: the full-interval multi-chip cost model, measured.

Decomposes the sparse backend's per-CD-interval cost on the real chip
into the pieces that scale differently with device count D, then
projects the D-device real-time curve.  Unlike round 4's
kernel-pairs-only table, every term is measured, and the replicated
terms (schedule build, refresh) are carried to the D -> infinity limit
— which is what exposes the column-replication ceiling.

Methodology notes:
* The axon tunnel costs ~0.1-0.25 ms per in-scan iteration and ~100 ms
  per dispatch, so every component is timed as an R-iteration lax.scan
  inside ONE jit with a data-dependent carry (no CSE/DCE), minus an
  empty-scan baseline.
* The CD share is CALIBRATED from the production chunk protocol
  (1000-step run_steps, ASAS on minus ASAS off minus amortized refresh)
  rather than a standalone CD call — a standalone call measures ~10 ms
  higher than the in-scan cost (no buffer donation), which would bias
  the projection pessimistic.

Writes output/full_interval.json and prints the D-projection table for
docs/PERF_ANALYSIS.md.

Run on the chip: python scripts/full_interval_model.py [N]
"""
import json
import os
import sys
import time

sys.path.insert(0, ".")
sys.path.insert(0, "scripts")

import jax
import jax.numpy as jnp
import numpy as np

import bench
from bluesky_tpu.core.asas import refresh_spatial_sort
from bluesky_tpu.core.step import SimConfig, run_steps
from bluesky_tpu.ops import cd_sched
from bluesky_tpu.ops.cd_tiled import block_reachability

NM, FT = 1852.0, 0.3048
RPZ, HPZ, TLOOK = 5 * NM, 1000 * FT, 300.0
BLOCK, EXTRA, S_CAP, WMAX = 256, 32, 6, 16
ICI_GBPS = 45.0                # v5e per-link ICI, conservative
COLL_LAT_US = 25.0             # per-collective launch+sync allowance
N_COLLECTIVES = 22             # HLO-verified count (21 AG + 1 AR)
COLL_BYTES_PER_AC = 90.0       # HLO-verified O(N) column gathers
SORT_EVERY = 30                # production refresh cadence (intervals)


def timed(fn, reps=100, outer=3, base=0.0):
    """ms per iteration of fn inside one jitted scan, baseline-corrected."""
    def body(c, _):
        return c + fn(c) * 1e-20, None

    run = jax.jit(lambda c: jax.lax.scan(body, c, None, length=reps)[0])
    c0 = jnp.float32(0.0)
    jax.block_until_ready(run(c0))
    best = 1e18
    for _ in range(outer):
        t0 = time.perf_counter()
        jax.block_until_ready(run(c0))
        best = min(best, time.perf_counter() - t0)
    return best / reps * 1e3 - base


def chunk_rate(state, cfg, nsteps=1000, reps=3, resort=False):
    """Wall s per sim-s over the production chunk protocol (donated).

    ``resort`` refreshes the spatial sort at each chunk edge exactly
    like bench.run_one / Simulation — without it the drifting fleet
    degrades the schedule and CD measures ~75% high."""
    def step(s):
        if resort:
            s = refresh_spatial_sort(s, cfg.asas, block=256,
                                     impl="sparse")
        return jax.block_until_ready(run_steps(s, cfg, nsteps))

    state = step(state)
    best = 1e18
    for _ in range(reps):
        t0 = time.perf_counter()
        state = step(state)
        best = min(best, time.perf_counter() - t0)
    return best / (nsteps * cfg.simdt), state


def measure(n):
    traf = bench._make_traffic(n, "continental", False, jnp.float32)
    ac = traf.state.ac
    cfg = SimConfig(cd_backend="sparse")
    acfg = cfg.asas
    st = refresh_spatial_sort(traf.state, acfg, block=256, impl="sparse")
    perm = st.asas.sort_perm
    n_tot = cd_sched.padded_size(n, 256)
    nb = n_tot // 256
    actf = ac.active.astype(jnp.float32)

    base_iter = timed(lambda c: c * 1.0000001, reps=400)

    # --- schedule build (scatter + trig is the replicated O(N) part;
    #     reach + windows are row-parallel and COULD shard) ---
    def sched_build(c):
        cols = cd_sched.scatter_padded(
            [ac.lat + c, ac.lon, ac.gs, ac.alt, ac.vs, actf], perm, n_tot)
        plat, plon, pgs, palt, pvs, pact = cols
        reach = block_reachability(plat, plon, pgs, pact > 0.5, nb,
                                   BLOCK, RPZ, TLOOK, alt=palt, vs=pvs,
                                   hpz=HPZ)
        stw, ln, _ = cd_sched.build_windows(reach, S_CAP, WMAX,
                                            pad_start=nb)
        return (jnp.sum(stw) + jnp.sum(ln)).astype(jnp.float32)

    t_sched = timed(sched_build, reps=100, base=base_iter)

    def scatter_part(c):
        cols = cd_sched.scatter_padded(
            [ac.lat + c, ac.lon, ac.gs, ac.alt, ac.vs, actf], perm, n_tot)
        return sum(jnp.sum(x) for x in cols)

    t_scatter = timed(scatter_part, reps=200, base=base_iter)

    # --- refresh (chunk-edge sort), one real call ---
    r_jit = jax.jit(lambda s: refresh_spatial_sort(
        s, acfg, block=256, impl="sparse").asas.sort_perm)
    jax.block_until_ready(r_jit(st))
    best = 1e18
    for _ in range(3):
        t0 = time.perf_counter()
        jax.block_until_ready(r_jit(st))
        best = min(best, time.perf_counter() - t0)
    t_refresh_call = best * 1e3

    # --- production chunk rates, ASAS on vs off (copies: donation) ---
    s_on, _ = chunk_rate(
        refresh_spatial_sort(jax.tree.map(jnp.array, traf.state), acfg,
                             block=256, impl="sparse"), cfg, resort=True)
    cfg_off = cfg._replace(asas=acfg._replace(swasas=False))
    s_off, _ = chunk_rate(jax.tree.map(jnp.array, traf.state), cfg_off)

    # per-interval (1 sim-s) shares; the chunk protocol refreshes once
    # per 50 sim-s, so remove that and re-amortize at SORT_EVERY below
    refresh_in_chunk = t_refresh_call / 50.0
    t_cd = s_on * 1e3 - s_off * 1e3 - refresh_in_chunk
    t_base = s_off * 1e3

    # --- scheduled pairs + interleaved imbalance (real schedule) ---
    from scaling_table import schedule_pairs_per_row
    per_row, _, n_over, _dest, _reach = schedule_pairs_per_row(
        ac.lat, ac.lon, ac.gs, ac.alt, ac.vs)
    return dict(
        n=n, nb=nb, t_sched_ms=round(t_sched, 2),
        t_scatter_ms=round(t_scatter, 2),
        t_cd_ms=round(t_cd, 2), t_base_ms=round(t_base, 2),
        t_refresh_call_ms=round(t_refresh_call, 1),
        x_realtime_1chip=round(1000.0 / (s_on * 1e3), 1),
        pairs=float(per_row.sum()), per_row=per_row.tolist(),
        overflow_rows=int(n_over))


def project(m, sort_every=SORT_EVERY, mode="replicate",
            spatial_fn=None, inscan=False, ds=None):
    """D -> projected ms/interval and x-realtime from the measured parts.

    ``mode='replicate'``: the column-replication scheme as implemented
    in round 4 — schedule build and refresh stay replicated (the ~200x
    ceiling).  ``mode='spatial'``: the ISSUE-5 domain decomposition as
    implemented — per-device scatter/trig/reach/windows over OWN
    stripes and a stripe-local share of the refresh, so every former
    O(N) replicated term scales ~1/D; the wire term is the measured
    halo + summary volume of the real per-D layout (``spatial_fn(d)``
    -> scaling_table.spatial_stats dict) instead of the O(N) column
    gathers.  The D=1 rows of both modes coincide with the measured
    single-chip interval (the calibration anchor).

    ``inscan=True`` (ISSUE 15): the sort refresh is folded into the
    compiled chunk, so the refresh term is amortized into the scan and
    its gather/argsort work rides the row sharding — it scales ~1/D in
    BOTH modes (spatial already did; the change is that the replicated
    decomposition loses its D-independent refresh floor, raising the
    D->inf ceiling).

    ``mode='tiles'`` (ISSUE 19): like spatial, but over the 2-D
    R x C lat x lon tile mesh — ``spatial_fn(d)`` should return
    scaling_table.tile_stats dicts, whose halo wire scales with the
    tile PERIMETER (a few blocks per canonical edge/corner offset)
    instead of the stripe width, and whose collective launch count is
    2 ppermutes per canonical offset (slab + gid) plus the summary
    gathers/psums."""
    per_row = np.asarray(m["per_row"])
    nb = len(per_row)
    # CD share splits: row-sharded pair work + the sched build that
    # runs inside it
    cd_rowshard = max(m["t_cd_ms"] - m["t_sched_ms"], 0.0)
    spatial = mode in ("spatial", "tiles")
    repl_fixed = 0.0 if spatial else m["t_sched_ms"]
    coll_bytes = COLL_BYTES_PER_AC * m["n"]
    ds = ds or (1, 2, 4, 8, 16, 32, 0)
    maxd = max(d for d in ds if d) if any(ds) else 32
    rows = []
    for d in ds:                           # 0 = the D->inf limit
        stats = None
        if spatial and d > 1 and spatial_fn is not None:
            stats = spatial_fn(d)
        if stats is not None:
            dev = np.asarray(stats["dev_pairs"], float)
            imb = dev.max() / max(dev.mean(), 1.0)
        elif d:
            nbp = -(-nb // d) * d
            rr = np.pad(per_row, (0, nbp - nb))
            dev = rr.reshape(nbp // d, d).T.sum(axis=1)
            imb = dev.max() / max(dev.mean(), 1.0)
        else:
            imb = 1.0
        inv = (1.0 / d) if d else 0.0
        if d == 1:
            coll = 0.0
        elif spatial:
            # halo slabs + summary metadata per device over ICI, ~12
            # collective launches for stripes (2 permutes, summary
            # gathers, count psums); tiles pay 2 ppermutes per
            # canonical offset (slab + gid) plus the same metadata
            # launches; D->inf keeps the (D-independent) halo volume
            # of the largest measured layout
            st = stats or (spatial_fn(maxd) if spatial_fn else None)
            wire = (st["halo_bytes_dev"] + st["summ_bytes"]) \
                if st else 2 * 16 * 256 * 16 * 4
            launches = (2 * len(st["offsets"]) + 8) \
                if st and "offsets" in st else 12
            coll = wire / (ICI_GBPS * 1e9) * 1e3 \
                + launches * COLL_LAT_US / 1e3
        else:
            coll = coll_bytes / (ICI_GBPS * 1e9) * 1e3 \
                + N_COLLECTIVES * COLL_LAT_US / 1e3
        sched = m["t_sched_ms"] * inv if spatial else repl_fixed
        refresh = m["t_refresh_call_ms"] / sort_every \
            * (inv if (spatial or inscan) else 1.0)
        interval = (cd_rowshard * inv * imb + sched
                    + m["t_base_ms"] * inv + refresh + coll)
        rows.append(dict(D=d or "inf",
                         cd_ms=round(cd_rowshard * inv * imb, 2),
                         repl_ms=round(sched, 2),
                         base_ms=round(m["t_base_ms"] * inv, 2),
                         refresh_ms=round(refresh, 2),
                         coll_ms=round(coll, 2),
                         interval_ms=round(interval, 2),
                         x_realtime=round(1000.0 / interval, 1)))
    return rows


def _spatial_fn_for(n):
    """Per-D spatial layout/halo stats on the benchmark fleet (the
    schedule-measured division of scaling_table.spatial_stats)."""
    from scaling_table import make_fleet, spatial_stats
    fleet = make_fleet(n, "continental")
    cache = {}

    def fn(d):
        if d not in cache:
            cache[d] = spatial_stats(*fleet, ndev=d)
        return cache[d]
    return fn


def _tiles_fn_for(n, geom="continental"):
    """Per-D 2-D tile layout/halo stats on the benchmark fleet: the
    schedule-measured division of scaling_table.tile_stats on the
    near-square R x C factorisation of d (the SHARD TILE default)."""
    from scaling_table import make_fleet, near_square_tiles, tile_stats
    fleet = make_fleet(n, geom)
    cache = {}

    def fn(d):
        if d not in cache:
            cache[d] = tile_stats(*fleet, tiles=near_square_tiles(d))
        return cache[d]
    return fn


def emit(m, per_row=None):
    """Project both decompositions from the measured terms, write the
    artifact, print the PERF_ANALYSIS tables."""
    if per_row is not None:
        m = dict(m, per_row=per_row)
    sfn = _spatial_fn_for(m["n"])
    tfn = _tiles_fn_for(m["n"])
    tfn_g = _tiles_fn_for(m["n"], geom="global")
    proj = project(m)
    proj_in = project(m, inscan=True)
    proj_sp = project(m, mode="spatial", spatial_fn=sfn)
    tile_ds = (1, 2, 4, 8, 16, 32, 64, 0)
    proj_t = project(m, mode="tiles", spatial_fn=tfn, ds=tile_ds)
    # D=64 occupancy check: count-proportional 2-D cuts should keep the
    # GLOBAL fleet's per-tile occupancy close to the continental one
    # (1-D stripes diverge — see scripts/scaling_table.py)
    occ64 = {}
    for geom, fn in (("continental", tfn), ("global", tfn_g)):
        st64 = fn(64)
        occ64[geom] = round(
            float(st64["counts"].max() / (m["n"] / 64)), 3)
    occ64["ratio"] = round(occ64["global"] / occ64["continental"], 3)
    mm = {k: v for k, v in m.items() if k != "per_row"}
    out = dict(measured=mm, projected=proj,
               projected_inscan=proj_in,
               projected_spatial=proj_sp,
               projected_tiles=proj_t,
               model=dict(ici_gbps=ICI_GBPS, coll_lat_us=COLL_LAT_US,
                          n_collectives=N_COLLECTIVES,
                          coll_bytes_per_ac=COLL_BYTES_PER_AC,
                          sort_every=SORT_EVERY,
                          spatial_collectives=12,
                          inscan_note=(
                              "projected_inscan folds the sort "
                              "refresh into the compiled chunk "
                              "(ISSUE 15): the replicated "
                              "decomposition's refresh term scales "
                              "1/D instead of staying a fixed floor, "
                              "raising the D->inf ceiling from "
                              f"{proj[-1]['x_realtime']}x to "
                              f"{proj_in[-1]['x_realtime']}x; the "
                              "spatial decomposition already "
                              "stripe-localized the refresh, so its "
                              "rows are unchanged by in-scan"),
                          spatial_halo=dict(
                              (d, {k: int(v) for k, v in sfn(d).items()
                                   if k in ("halo_blocks", "halo_need",
                                            "halo_bytes_dev",
                                            "summ_bytes", "nb_local")})
                              for d in (2, 4, 8, 16, 32)),
                          tile_halo=dict(
                              (d, dict(
                                  tiles="x".join(map(str,
                                                     tfn(d)["tiles"])),
                                  offsets=len(tfn(d)["offsets"]),
                                  halo_need=list(tfn(d)["halo_need"]),
                                  budgets=list(tfn(d)["budgets"]),
                                  wire_blocks=int(tfn(d)["wire_blocks"]),
                                  halo_bytes_dev=int(
                                      tfn(d)["halo_bytes_dev"]),
                                  summ_bytes=int(tfn(d)["summ_bytes"]),
                                  nb_local=int(tfn(d)["nb_local"]),
                                  uncovered=int(tfn(d)["uncovered"])))
                              for d in (4, 8, 16, 32, 64)),
                          tiles_occupancy_d64=occ64,
                          tiles_note=(
                              "projected_tiles: 2-D lat x lon tile "
                              "decomposition (ISSUE 19) — halo wire "
                              "scales with the tile perimeter (a few "
                              "blocks per canonical edge/corner "
                              "offset) instead of the stripe width, "
                              "and the count-proportional 2-D cuts "
                              "keep global-geometry occupancy within "
                              f"{occ64['ratio']}x of continental at "
                              "D=64 where 1-D stripes diverge")))
    # fresh checkout: output/ may not exist yet — a multi-minute run
    # must not crash at the final dump
    os.makedirs("output", exist_ok=True)
    with open("output/full_interval.json", "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(mm))
    for title, p in (("column-replication (as implemented)", proj),
                     ("column-replication + in-scan refresh", proj_in),
                     ("spatial decomposition (as implemented)", proj_sp),
                     ("2-D lat x lon tiles (as implemented)", proj_t)):
        print(f"\n{title}:")
        print("| D | CD | sched | base | refresh | coll | "
              "interval ms | x-realtime |")
        print("|---|---|---|---|---|---|---|---|")
        for r in p:
            print(f"| {r['D']} | {r['cd_ms']} | {r['repl_ms']} | "
                  f"{r['base_ms']} | {r['refresh_ms']} | {r['coll_ms']} | "
                  f"{r['interval_ms']} | {r['x_realtime']} |")
    return out


def main(n=100_000):
    emit(measure(n))


def reproject(path="BENCH_FULL_INTERVAL.json"):
    """Recompute the projections (incl. the spatial decomposition and
    the in-scan refresh variant) from a previously measured artifact's
    terms — the chip-measured D=1 numbers stay authoritative, only the
    D-scaling model and the schedule-measured layout stats
    (CPU-computable) are refreshed.  Writes the regenerated projection
    rows back into ``path`` and merges the model-projected in-scan
    20-step chunk row into BENCH_CHUNK_SWEEP.json.  Run after changing
    the decomposition without chip access:
    ``python scripts/full_interval_model.py --reproject``."""
    with open(path) as f:
        old = json.load(f)
    m = old["measured"]
    # per-row pairs re-derived from the same deterministic benchmark
    # fleet the measurement used (dropped from the artifact for size)
    from scaling_table import schedule_pairs_per_row
    traf = bench._make_traffic(m["n"], "continental", False, jnp.float32)
    ac = traf.state.ac
    per_row, _, _, _, _ = schedule_pairs_per_row(
        ac.lat, ac.lon, ac.gs, ac.alt, ac.vs)
    out = emit(m, per_row=per_row.tolist())
    # sections emit() does not recompute (e.g. the measured host-CPU
    # mesh rows from --cpu-mesh) survive the rewrite
    for k, v in old.items():
        out.setdefault(k, v)
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(f"\nwrote {path}")
    merge_projected_chunk_row(m)
    return out


def merge_projected_chunk_row(m, chunk=20,
                              path="BENCH_CHUNK_SWEEP.json"):
    """Model-projected in-scan 20-step chunk row for the chip sweep.

    The measured tpu:v5e sweep pays a host refresh dispatch per chunk
    edge — at 20-step chunks that is most of the interactive gap.  With
    the refresh in-scan, the 20-step interval is the FF interval minus
    the FF protocol's amortized host refresh (one call per 50 sim-s in
    run_steps' chunk protocol) plus the on-device refresh at the true
    sort_every cadence; pipelined dispatch hides the remaining edge.
    The row is merged next to the measured sweep (same platform /
    backend / n, protocol marks it model-projected) and skipped from
    BENCH_HISTORY — it is a projection, not a measurement."""
    if not os.path.isfile(path):
        return None
    with open(path) as f:
        doc = json.load(f)
    rows = doc.get("rows", doc if isinstance(doc, list) else [])
    ff = None
    for r in rows:
        if (r.get("platform") == "tpu:v5e" and r.get("n") == m["n"]
                and r.get("backend") == "sparse"
                and "projected" not in (r.get("protocol") or "")):
            if ff is None or r.get("nsteps_chunk", 0) > ff["nsteps_chunk"]:
                ff = r
    if ff is None:
        print("no measured tpu:v5e sweep rows; projected row skipped")
        return None
    interval_ff = 1000.0 / ff["x_realtime"]
    refresh_host = m["t_refresh_call_ms"] / 50.0   # FF chunk cadence
    refresh_inscan = m["t_refresh_call_ms"] / SORT_EVERY
    interval = interval_ff - refresh_host + refresh_inscan
    x = round(1000.0 / interval, 1)
    proto = ("model-projected (full-interval reprojection), "
             "in-scan sort refresh")
    row = dict(n=m["n"], backend="sparse", geometry="continental",
               nsteps_chunk=chunk, platform="tpu:v5e",
               x_realtime=x,
               gap_vs_ff=round(x / ff["x_realtime"], 3),
               interval_ms=round(interval, 2),
               interval_ff_ms=round(interval_ff, 2),
               refresh_host_ms=round(refresh_host, 2),
               refresh_inscan_ms=round(refresh_inscan, 2),
               protocol=proto)
    rows = [r for r in rows if (r.get("protocol") != proto
                                or r.get("nsteps_chunk") != chunk)]
    rows.append(row)
    from chunk_sweep import add_gap_vs_ff
    add_gap_vs_ff(rows)          # kept rows gain the column too
    bench.write_bench_json(path, rows, history=False)
    print(f"merged projected in-scan {chunk}-step row into {path}: "
          f"x_realtime {x} (gap_vs_ff {row['gap_vs_ff']}) vs FF "
          f"{ff['x_realtime']}")
    return row


def measure_cpu_mesh(n=100_000, path="BENCH_FULL_INTERVAL.json",
                     total_steps=40, chunk=20):
    """Measured replicate-vs-stripes-vs-tiles rows on the host CPU
    mesh (ISSUE 19 acceptance).  Run with
    ``XLA_FLAGS=--xla_force_host_platform_device_count=8`` so all
    three decompositions execute on a REAL 8-device mesh — the
    collectives, halo exchange and re-bucketing all run for real; only
    the absolute ms are host-CPU, so the rows are a mode-vs-mode
    comparison, not a chip measurement (the chip terms above stay
    authoritative).  Also records the schedule-measured halo wire of
    stripes vs tiles on the GLOBAL scene — the acceptance bound is
    tiles <= stripes there, where the 1-D stripe must ship its full
    360-degree-wide boundary and the tile only its perimeter."""
    import jax
    ndev = len(jax.devices())
    from scaling_table import (make_fleet, near_square_tiles,
                               spatial_stats, tile_stats)
    tiles = near_square_tiles(ndev)
    rows = []
    for shard in ("replicate", "spatial", "tiles"):
        t0 = time.perf_counter()
        row = bench.run_chunked(n, chunk=chunk, total_steps=total_steps,
                                reps=1, shard=shard, shard_devices=ndev)
        row["platform"] = bench.platform_tag()
        row["protocol"] += (f"; {ndev}-device host-CPU mesh "
                            "(mode-vs-mode comparison row)")
        rows.append(row)
        print(f"[cpu-mesh] {shard}: x_realtime {row['x_realtime']} "
              f"({time.perf_counter() - t0:.0f}s)", flush=True)
    fleet = make_fleet(n, "global")
    sp = spatial_stats(*fleet, ndev=ndev)
    ti = tile_stats(*fleet, tiles=tiles)
    halo = dict(
        n=n, geometry="global", ndev=ndev,
        tiles="x".join(map(str, tiles)),
        stripes_halo_bytes_dev=int(sp["halo_bytes_dev"]),
        tiles_halo_bytes_dev=int(ti["halo_bytes_dev"]),
        tiles_le_stripes=bool(int(ti["halo_bytes_dev"])
                              <= int(sp["halo_bytes_dev"])),
        stripes_wire_blocks=2 * int(sp["halo_blocks"]),
        tiles_wire_blocks=int(ti["wire_blocks"]),
        tiles_uncovered=int(ti["uncovered"]))
    with open(path) as f:
        doc = json.load(f)
    doc["measured_cpu_mesh"] = dict(
        ndev=ndev, chunk=chunk, total_steps=total_steps, rows=rows,
        halo_global=halo,
        note=("replicate vs 1-D stripes vs 2-D tiles on a forced "
              f"{ndev}-device host-CPU mesh; collectives and halo "
              "exchange execute for real, absolute ms are host-CPU"))
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
    print(json.dumps(doc["measured_cpu_mesh"]["halo_global"]))
    print(f"wrote {path} (measured_cpu_mesh, {len(rows)} rows)")
    return doc["measured_cpu_mesh"]


if __name__ == "__main__":
    if "--cpu-mesh" in sys.argv:
        args = [a for a in sys.argv[1:] if not a.startswith("--")]
        measure_cpu_mesh(int(args[0]) if args else 100_000)
    elif "--reproject" in sys.argv:
        reproject()
    else:
        main(int(sys.argv[1]) if len(sys.argv) > 1 else 100_000)
