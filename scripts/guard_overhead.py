"""Measure the in-scan integrity-guard overhead (ISSUE 1 acceptance:
the finite-check folded into the lax.scan carry must cost < 2% on the
N=100k sparse bench row).

Protocol matches bench.run_one / BENCH_CHUNK_SWEEP.json exactly: same
traffic generator, same backend pick, host re-sort per chunk, best-of-3
reps — run twice per configuration, once with ``run_steps`` and once
with ``run_steps_checked``, on the SAME warmed state.  Output rows land
in BENCH_GUARD.json with both rates and the relative overhead.

Usage: python scripts/guard_overhead.py [N] [nsteps_chunk]
  (defaults: N=100000, chunk=1000 — the headline protocol.  On a
  CPU-only box the sparse backend is unavailable; pass a smaller N,
  e.g. 2048, and the dense/tiled pick + platform are recorded in the
  protocol field so rows are never silently comparable.)
"""
import json
import os
import sys
import time

sys.path.insert(0, ".")

import bench  # noqa: E402


def run_pair(n_ac, nsteps=1000, reps=3, backend=None, geometry=None):
    import jax
    import jax.numpy as jnp
    from bluesky_tpu.core.asas import impl_for_backend, refresh_spatial_sort
    from bluesky_tpu.core.step import (SimConfig, run_steps,
                                       run_steps_checked)

    backend = backend or bench._pick_backend(n_ac)
    geometry = geometry or ("continental" if n_ac > 16384 else "regional")
    traf = bench._make_traffic(n_ac, geometry, backend == "dense",
                               jnp.float32)
    cfg = SimConfig(cd_backend=backend)

    def resort(st):
        if backend in ("tiled", "pallas", "sparse"):
            return refresh_spatial_sort(st, cfg.asas, block=cfg.cd_block,
                                        impl=impl_for_backend(backend))
        return st

    # Both variants must traverse the IDENTICAL trajectory — the CD
    # workload depends on conflict density, which drifts as the fleet
    # disperses — so each starts from a copy of the same initial state
    # (copied because run_steps donates its input buffers).
    state0 = traf.state

    def bench_fn(fn):
        state = fn(resort(jax.tree.map(jnp.copy, state0)), cfg,
                   nsteps)                           # warmup/compile
        jax.block_until_ready(state)
        best = float("inf")
        state = jax.tree.map(jnp.copy, state0)
        for _ in range(reps):
            t0 = time.perf_counter()
            state = fn(resort(state), cfg, nsteps)
            jax.block_until_ready(state)
            best = min(best, time.perf_counter() - t0)
        return best

    def checked(st, cfg, nsteps):
        st, _bad = run_steps_checked(st, cfg, nsteps)
        return st

    t_plain = bench_fn(run_steps)
    t_guard = bench_fn(checked)
    rate = lambda t: n_ac * nsteps / t
    return dict(
        n=n_ac, backend=backend, geometry=geometry,
        nsteps_chunk=nsteps,
        ac_steps_per_s_unguarded=round(rate(t_plain), 1),
        ac_steps_per_s_guarded=round(rate(t_guard), 1),
        overhead_pct=round(100.0 * (t_guard - t_plain) / t_plain, 2),
        protocol=(f"best-of-{reps}, host re-sort per chunk, "
                  f"platform={jax.devices()[0].platform}"),
    )


def main(n_ac=100_000, nsteps=1000):
    row = run_pair(n_ac, nsteps=nsteps)
    print(json.dumps(row), flush=True)
    rows = []
    if os.path.isfile("BENCH_GUARD.json"):
        with open("BENCH_GUARD.json") as f:
            rows = json.load(f)
    if isinstance(rows, dict):              # shared writer format
        rows = rows.get("rows", [])
    rows = [r for r in rows
            if (r["n"], r["nsteps_chunk"]) != (row["n"],
                                               row["nsteps_chunk"])]
    rows.append(row)
    # shared writer: platform tag + {"rows": ...} shape; only the new
    # row is history (the deduped survivors were recorded by their own
    # runs)
    bench.write_bench_json("BENCH_GUARD.json", rows, history=False)
    bench.append_history("BENCH_GUARD", [row])
    os.makedirs("output", exist_ok=True)
    with open("output/guard_overhead.json", "w") as f:
        json.dump(rows, f, indent=1)
    return row


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 100_000,
         int(sys.argv[2]) if len(sys.argv) > 2 else 1000)
