"""Round-3 TPU profiling: where does the 100k-continental wall time go?

Measures, strictly serially on the one real chip (axon tunnel rules:
amortize the ~80 ms dispatch latency, keep every device program well
under the ~1 min watchdog):

  1. CD sweep (pallas, current):     per-sweep ms
  2. CD program-overhead probe:      same kernel, all aircraft inactive
     (every tile skips by any(pairmask) -> time = grid+DMA overhead only)
  3. Full pipeline (current bench):  ms/step
  4. Pipeline, ASAS off:             ms/step (FMS+kinematics+perf)
  5. Pipeline, ASAS+FMS off:         ms/step (kinematics+perf only)
  6. spatial_permutation:            ms (the cached Morton argsort)
  7. MVP resolve_from_sums + partner bookkeeping: ms (the ASAS tail)

Run: python scripts/profile_r3.py   (on the TPU host, nothing else running)
"""
import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, reps=5, warmup=2):
    for _ in range(warmup):
        jax.block_until_ready(fn())
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        ts.append(time.perf_counter() - t0)
    return min(ts)


def main():
    from bench import _make_traffic
    from bluesky_tpu.core.step import SimConfig, run_steps
    from bluesky_tpu.ops import cd_pallas, cr_mvp, cd_tiled

    n = 100_000
    print(f"backend: {jax.default_backend()}, N={n} continental")
    traf = _make_traffic(n, "continental", False, jnp.float32)
    ac = traf.state.ac
    asas = traf.state.asas
    NMm, FT = 1852.0, 0.3048
    mcfg = cr_mvp.MVPConfig(rpz_m=5 * NMm * 1.05, hpz_m=1000 * FT * 1.05,
                            tlookahead=300.0)

    # --- 1. CD sweep, current kernel (includes the cached-perm sort path
    # as used in-step?  No: raw kernel, fresh perm each call is how the
    # bench cd_pairs_per_s measures; time both with and without perm).
    perm = cd_tiled.spatial_permutation(ac.lat, ac.lon, ac.active)
    perm = jax.block_until_ready(perm.astype(jnp.int32))
    args = (ac.lat, ac.lon, ac.trk, ac.gs, ac.alt, ac.vs,
            ac.gseast, ac.gsnorth, ac.active, asas.noreso)

    cd_cached = jax.jit(lambda: cd_pallas.detect_resolve_pallas(
        *args, 5 * NMm, 1000 * FT, 300.0, mcfg, perm=perm).inconf)
    t = timeit(cd_cached)
    print(f"1. CD sweep (pallas, cached perm): {t*1e3:.1f} ms")

    # --- 2. overhead probe: all-inactive fleet, same shapes
    inact = jnp.zeros_like(ac.active)
    cd_dead = jax.jit(lambda: cd_pallas.detect_resolve_pallas(
        ac.lat, ac.lon, ac.trk, ac.gs, ac.alt, ac.vs,
        ac.gseast, ac.gsnorth, inact, asas.noreso,
        5 * NMm, 1000 * FT, 300.0, mcfg, perm=perm).inconf)
    t = timeit(cd_dead)
    print(f"2. CD all-inactive (pure grid+DMA overhead): {t*1e3:.1f} ms")

    # 2b. no-prefilter variant: every tile computed -> pair cost slope
    cd_nopf = jax.jit(lambda: cd_pallas.detect_resolve_pallas(
        *args, 5 * NMm, 1000 * FT, 300.0, mcfg, perm=perm,
        spatial_sort=False).inconf)
    t_nopf = timeit(cd_nopf, reps=2, warmup=1)
    print(f"2b. CD unsorted slots (reach skip ~useless): {t_nopf*1e3:.1f} ms")

    # --- 3-5. pipeline splits (100 steps per chunk, 3 reps)
    nsteps = 100

    def run(cfg):
        tr = _make_traffic(n, "continental", False, jnp.float32)
        st = run_steps(tr.state, cfg, nsteps)      # compile+warm
        jax.block_until_ready(st)
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            st = run_steps(st, cfg, nsteps)
            jax.block_until_ready(st)
            ts.append(time.perf_counter() - t0)
        return min(ts) / nsteps

    t3 = run(SimConfig(cd_backend="pallas"))
    print(f"3. full pipeline: {t3*1e3:.2f} ms/step "
          f"({0.05/t3:.1f}x realtime)")
    from bluesky_tpu.core.asas import AsasConfig
    t4 = run(SimConfig(cd_backend="pallas", asas=AsasConfig(swasas=False)))
    print(f"4. ASAS off: {t4*1e3:.2f} ms/step")
    t5 = run(SimConfig(cd_backend="pallas", asas=AsasConfig(swasas=False),
                       fms_dt=1e9))
    print(f"5. ASAS+FMS off: {t5*1e3:.2f} ms/step")

    # --- 6. sort cost
    sortfn = jax.jit(lambda la, lo, a: cd_tiled.spatial_permutation(la, lo, a))
    t6 = timeit(lambda: sortfn(ac.lat, ac.lon, ac.active))
    print(f"6. spatial_permutation (argsort 100k): {t6*1e3:.1f} ms")

    # --- 7. ASAS tail: resolve_from_sums + partner ops on dummy data
    rd = jax.block_until_ready(jax.jit(
        lambda: cd_pallas.detect_resolve_pallas(
            *args, 5 * NMm, 1000 * FT, 300.0, mcfg, perm=perm))())

    def tail():
        out = cr_mvp.resolve_from_sums(
            rd.sum_dve, rd.sum_dvn, rd.sum_dvv, rd.tsolv,
            ac.alt, ac.gseast, ac.gsnorth, ac.vs, ac.trk, ac.gs,
            ac.selalt, traf.state.ap.vs, asas.alt,
            100.0, 300.0, -15.0, 15.0, mcfg, resooff=asas.resooff)
        keep = cd_tiled.partner_keep(
            asas.partners, ac.lat, ac.lon, ac.gseast, ac.gsnorth,
            ac.trk, ac.active, 5 * NMm, 5 * NMm * 1.05)
        merged = cd_tiled.merge_partners(
            cd_tiled.topk_partners(rd, 8), asas.partners, keep)
        return out[0], merged
    t7 = timeit(jax.jit(tail))
    print(f"7. MVP tail + partner bookkeeping: {t7*1e3:.2f} ms")


if __name__ == "__main__":
    main()
