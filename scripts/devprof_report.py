"""Merge a device-profile window (obs/devprof.py) with host recorder
dumps and print the per-chunk device-time attribution table.

``PROFILE DEVICE`` wraps N chunk dispatches in a ``jax.profiler`` trace
window.  Two artifact families come out of one window:

* host recorder dumps (``trace-*.json``) carrying the ``devprof_chunk``
  complete events — one per chunk, with the attribution split already
  measured at the host edge (compute / halo-collective / host-edge ms)
  — plus the ``device_profile`` span that brackets the whole window;
* the XLA trace under ``<dir>/plugins/profile/<ts>/*.trace.json.gz``
  (gzipped Chrome trace-event JSON on CPU/TPU alike).

This script concatenates both into ONE Perfetto JSON (``-o``) so the
host spans and the device timeline land on a shared axis, and prints a
table from the ``devprof_chunk`` events:

    seq  chunk  compute_ms  halo_ms  edge_ms  device%

Run:
    python scripts/devprof_report.py trace-*.json \
        [--profile-dir RUNDIR/devprof] [-o merged.json]
"""
import argparse
import glob
import gzip
import json
import os
import sys

# reuse the recorder-dump loader (shared dedupe semantics)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import trace_report


def load_xla_traces(profile_dir):
    """Glob the jax.profiler output tree for Chrome-trace files and
    return their concatenated traceEvents."""
    events = []
    pats = (os.path.join(profile_dir, "plugins", "profile",
                         "*", "*.trace.json.gz"),
            os.path.join(profile_dir, "plugins", "profile",
                         "*", "*.trace.json"))
    paths = sorted(p for pat in pats for p in glob.glob(pat))
    for p in paths:
        try:
            opener = gzip.open if p.endswith(".gz") else open
            with opener(p, "rt") as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"skipping {p}: {e}", file=sys.stderr)
            continue
        evs = doc.get("traceEvents", []) if isinstance(doc, dict) \
            else doc
        for ev in evs:
            if isinstance(ev, dict):
                events.append(ev)
    return events, paths


def attribution_rows(events):
    """Rows from devprof_chunk complete events (host recorder), sorted
    by seq.  Schema is pinned by tests/test_devprof.py."""
    rows = []
    for ev in events:
        if ev.get("name") != "devprof_chunk" or ev.get("ph") != "X":
            continue
        args = ev.get("args") or {}
        rows.append({
            "seq": args.get("seq"),
            "chunk": args.get("chunk"),
            "compute_ms": args.get("compute_ms"),
            "halo_ms": args.get("halo_ms"),
            "edge_ms": args.get("edge_ms"),
        })
    rows.sort(key=lambda r: (r["seq"] is None, r["seq"]))
    return rows


def print_table(rows, out=sys.stdout):
    head = (f"{'seq':>5} {'chunk':>6} {'compute_ms':>11} "
            f"{'halo_ms':>9} {'edge_ms':>9} {'device%':>8}")
    print(head, file=out)
    print("-" * len(head), file=out)
    for r in rows:
        c = r.get("compute_ms") or 0.0
        h = r.get("halo_ms") or 0.0
        e = r.get("edge_ms") or 0.0
        tot = c + h + e
        pct = (100.0 * c / tot) if tot else 0.0
        print(f"{str(r.get('seq', '')):>5} {str(r.get('chunk', '')):>6}"
              f" {c:>11.2f} {h:>9.2f} {e:>9.2f} {pct:>7.1f}%",
              file=out)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("dumps", nargs="*",
                    help="host recorder trace-*.json dump files")
    ap.add_argument("--profile-dir", default=None,
                    help="PROFILE DEVICE output dir (holds the "
                         "plugins/profile XLA trace tree)")
    ap.add_argument("-o", "--out", default=None,
                    help="write the merged Perfetto trace here")
    args = ap.parse_args(argv)

    host = trace_report.load(args.dumps) if args.dumps else []
    device, dev_paths = ([], [])
    if args.profile_dir:
        device, dev_paths = load_xla_traces(args.profile_dir)
        if not dev_paths:
            print(f"no XLA trace files under {args.profile_dir}",
                  file=sys.stderr)
    if not host and not device:
        print("no events found", file=sys.stderr)
        return 1

    if args.out:
        doc = trace_report.merge(
            host + device,
            {"sources": list(args.dumps) + dev_paths})
        with open(args.out, "w") as f:
            json.dump(doc, f)
        print(f"merged {len(host)} host + {len(device)} device "
              f"events -> {args.out}")

    rows = attribution_rows(host)
    if rows:
        print_table(rows)
    else:
        print("no devprof_chunk events in the host dumps "
              "(was a PROFILE DEVICE window active?)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
