"""CI obs-smoke: the ISSUE-11 observability contract, measured.

Two halves:

1. Parity — the flight recorder and metrics registry are host-side
   only: a run with the recorder ENABLED must produce a bit-identical
   stepped state to a run with it disabled (the instrumentation adds
   zero device ops).  Hash mismatch is a hard failure.

2. Overhead — best-of-reps wall time for the same scenario with the
   recorder off vs on.  The contract is <2% added wall; the CI lane
   flags (non-blocking) above 5% because shared runners are noisy.
   Rows land in BENCH_OBS.json; a sample merged Perfetto trace is
   written next to it so every PR ships an openable timeline.

Exit 0 on success, 1 on parity failure or >5% measured overhead.

Usage: python scripts/obs_smoke.py [--reps 3] [--out BENCH_OBS.json]
"""
import argparse
import hashlib
import json
import os
import sys
import time

sys.path.insert(0, ".")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


def state_hash(sim):
    import jax
    h = hashlib.sha256()
    for leaf in jax.tree.leaves(jax.tree.map(np.asarray, sim.traf.state)):
        h.update(np.ascontiguousarray(leaf).tobytes())
    h.update(repr([sim.traf.ids, sim.traf.types]).encode())
    return h.hexdigest()


def build(nmax=64):
    from bluesky_tpu.simulation.sim import Simulation
    sim = Simulation(nmax=nmax)
    for cmd in (
            "CRE KL1 B744 52 4 90 FL200 250",
            "CRE KL2 B744 52.2 4.3 270 FL210 250",
            "CRE KL3 B744 52.1 4.1 180 FL205 240",
            "SCHEDULE 00:00:03 ALT KL1 FL300",
            "SCHEDULE 00:00:06 CRE KL4 B744 53 5 180 FL100 200",
            "SCHEDULE 00:00:09 DEL KL2"):
        sim.stack.stack(cmd)
    sim.stack.process()
    sim.op()
    # op() clears ffmode, so engage fast-forward AFTER it — the timed
    # reps must be compute-bound, not wall-clock paced, for the
    # overhead percentage to mean anything
    sim.fastforward()
    return sim


def run_once(trace: bool, until=20.0):
    from bluesky_tpu.obs.trace import get_recorder
    rec = get_recorder()
    rec.clear()
    if trace:
        rec.enable()
    else:
        rec.disable()
    sim = build()
    t0 = time.perf_counter()
    sim.run(until_simt=until, max_iters=2000)
    wall = time.perf_counter() - t0
    return sim, wall


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--out", default="BENCH_OBS.json")
    ap.add_argument("--trace-out", default="output/obs")
    args = ap.parse_args(argv)

    import bluesky_tpu.settings as settings
    os.makedirs(args.trace_out, exist_ok=True)
    settings.trace_dir = args.trace_out

    # warmup: pays every jit compile so the timed reps hit cache
    run_once(False)

    # ---- parity: recorder on must not change the stepped state
    sim_off, _ = run_once(False)
    sim_on, _ = run_once(True)
    h_off, h_on = state_hash(sim_off), state_hash(sim_on)
    assert h_off == h_on, (
        f"recorder on/off state hash diverged:\n"
        f"  off {h_off}\n  on  {h_on}")
    n_chunks = sim_on.pipe_stats["pipelined_chunks"] \
        + sim_on.pipe_stats["sync_chunks"]
    lat = sim_on.obs.get("sim_chunk_latency_ms")
    assert lat is not None and lat.count > 0, \
        "chunk-latency histogram never observed a sample"
    print(f"parity OK: hash {h_off[:16]}..., {n_chunks} chunks, "
          f"latency p50 {lat.percentile(0.5):.2f} ms")

    # ---- sample trace: dump the enabled run's ring + merge it
    from bluesky_tpu.obs.trace import get_recorder
    rec = get_recorder()
    n_events = len(rec)
    path = rec.dump(reason="smoke", proc="sim")
    assert path, "enabled run left an empty trace ring"
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import trace_report
    events = trace_report.load([path])
    merged_path = os.path.join(args.trace_out, "trace_sample.json")
    with open(merged_path, "w") as f:
        json.dump(trace_report.merge(events), f)
    rows, _ = trace_report.chunk_table(events)
    assert rows, "merged trace has no per-chunk rows"
    print(f"sample trace: {n_events} events, {len(rows)} chunk rows "
          f"-> {merged_path}")
    rec.disable()
    rec.clear()

    # ---- overhead: alternate off/on reps, keep the best of each
    wall_off, wall_on = np.inf, np.inf
    for _ in range(args.reps):
        _, w = run_once(False)
        wall_off = min(wall_off, w)
        _, w = run_once(True)
        wall_on = min(wall_on, w)
    overhead = (wall_on - wall_off) / wall_off * 100.0
    row = {
        "scenario": "obs_smoke 4-aircraft FF to simt=20",
        "reps": args.reps,
        "wall_off_s": round(wall_off, 4),
        "wall_on_s": round(wall_on, 4),
        "overhead_pct": round(overhead, 2),
        "trace_events": n_events,
        "chunks": int(n_chunks),
        "parity": "bit-identical",
        "protocol": f"best-of-{args.reps}, alternating off/on, "
                    f"platform={os.environ.get('JAX_PLATFORMS', '?')}",
    }
    # shared writer: platform tag + BENCH_HISTORY append (the perf
    # sentinel's obs-overhead series)
    import bench
    bench.write_bench_json(args.out, [row])
    print(f"overhead: off {wall_off:.3f}s vs on {wall_on:.3f}s "
          f"= {overhead:+.2f}% -> {args.out}")
    if overhead > 5.0:
        print("OBS SMOKE: overhead above the 5% CI flag line",
              file=sys.stderr)
        return 1
    print("obs smoke OK")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except AssertionError as e:
        print(f"OBS SMOKE FAILED: {e}", file=sys.stderr)
        sys.exit(1)
