"""CI obs-smoke: the ISSUE-11 observability contract, measured.

Two halves, run three times (flight recorder, ISSUE-14 scanstats, and
the ISSUE-17 SDC state fingerprint):

1. Parity — the instrumentation is carry/host-side only: a run with
   the recorder ENABLED must produce a bit-identical stepped state to
   a run with it disabled (zero added device ops), a run with
   SCANSTATS on must match both (the accumulator folds read state,
   never write it), and a run with FINGERPRINT on must match too (the
   fold is an int32 XOR chain riding the carry — it reads the state,
   never writes it).  Hash mismatch is a hard failure.

2. Overhead — best-of-reps wall time for the same scenario with each
   instrument off vs on.  The contract is <2% added wall; the CI lane
   flags above 5% because shared runners are noisy.  A row pair per
   instrument lands in BENCH_OBS.json; a sample merged Perfetto trace
   is written next to it so every PR ships an openable timeline.

Exit 0 on success, 1 on parity failure or >5% measured overhead.

Usage: python scripts/obs_smoke.py [--reps 3] [--out BENCH_OBS.json]
"""
import argparse
import hashlib
import json
import os
import sys
import time

sys.path.insert(0, ".")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


def state_hash(sim):
    import jax
    h = hashlib.sha256()
    for leaf in jax.tree.leaves(jax.tree.map(np.asarray, sim.traf.state)):
        h.update(np.ascontiguousarray(leaf).tobytes())
    h.update(repr([sim.traf.ids, sim.traf.types]).encode())
    return h.hexdigest()


def build(nmax=64):
    from bluesky_tpu.simulation.sim import Simulation
    sim = Simulation(nmax=nmax)
    for cmd in (
            "CRE KL1 B744 52 4 90 FL200 250",
            "CRE KL2 B744 52.2 4.3 270 FL210 250",
            "CRE KL3 B744 52.1 4.1 180 FL205 240",
            "SCHEDULE 00:00:03 ALT KL1 FL300",
            "SCHEDULE 00:00:06 CRE KL4 B744 53 5 180 FL100 200",
            "SCHEDULE 00:00:09 DEL KL2"):
        sim.stack.stack(cmd)
    sim.stack.process()
    sim.op()
    # op() clears ffmode, so engage fast-forward AFTER it — the timed
    # reps must be compute-bound, not wall-clock paced, for the
    # overhead percentage to mean anything
    sim.fastforward()
    return sim


def run_once(trace: bool, until=20.0, scanstats=False,
             fingerprint=False):
    from bluesky_tpu.obs.trace import get_recorder
    rec = get_recorder()
    rec.clear()
    if trace:
        rec.enable()
    else:
        rec.disable()
    sim = build()
    if scanstats:
        sim.set_scanstats(True)
    if fingerprint:
        sim.set_fingerprint(True)
    t0 = time.perf_counter()
    sim.run(until_simt=until, max_iters=2000)
    wall = time.perf_counter() - t0
    return sim, wall


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--out", default="BENCH_OBS.json")
    ap.add_argument("--trace-out", default="output/obs")
    args = ap.parse_args(argv)

    import bluesky_tpu.settings as settings
    os.makedirs(args.trace_out, exist_ok=True)
    settings.trace_dir = args.trace_out

    # warmup: pays every jit compile so the timed reps hit cache
    run_once(False)
    run_once(False, scanstats=True)
    run_once(False, fingerprint=True)

    # ---- parity: recorder on must not change the stepped state, and
    # the scanstats fold (pure carry reads) must not either — all
    # three hashes are the ISSUE-11/14 off-path bit-identity contract
    sim_off, _ = run_once(False)
    h_off = state_hash(sim_off)
    sim_ss, _ = run_once(False, scanstats=True)
    h_ss = state_hash(sim_ss)
    assert h_ss == h_off, (
        f"scanstats on/off state hash diverged:\n"
        f"  off {h_off}\n  on  {h_ss}")
    assert sim_ss._scan_last is not None \
        and sim_ss.obs.get("sim_scan_steps") is not None, \
        "scanstats run drained no accumulator pack"
    # fingerprint parity (ISSUE-17): the fold reads the carry, never
    # writes state — ON must be bit-identical to OFF, and the run must
    # actually have chained a per-chunk fingerprint word
    sim_fp, _ = run_once(False, fingerprint=True)
    h_fp = state_hash(sim_fp)
    assert h_fp == h_off, (
        f"fingerprint on/off state hash diverged:\n"
        f"  off {h_off}\n  on  {h_fp}")
    fp = sim_fp.fp_summary()
    assert fp is not None and fp["chunks"] > 0, \
        "fingerprint run chained no chunk fingerprints"
    # the recorder run goes LAST: run_once clears the ring, and the
    # sample-trace section below dumps this run's events
    sim_on, _ = run_once(True)
    h_on = state_hash(sim_on)
    assert h_off == h_on, (
        f"recorder on/off state hash diverged:\n"
        f"  off {h_off}\n  on  {h_on}")
    n_chunks = sim_on.pipe_stats["pipelined_chunks"] \
        + sim_on.pipe_stats["sync_chunks"]
    lat = sim_on.obs.get("sim_chunk_latency_ms")
    assert lat is not None and lat.count > 0, \
        "chunk-latency histogram never observed a sample"
    print(f"parity OK: hash {h_off[:16]}..., {n_chunks} chunks, "
          f"latency p50 {lat.percentile(0.5):.2f} ms")

    # ---- sample trace: dump the enabled run's ring + merge it
    from bluesky_tpu.obs.trace import get_recorder
    rec = get_recorder()
    n_events = len(rec)
    path = rec.dump(reason="smoke", proc="sim")
    assert path, "enabled run left an empty trace ring"
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import trace_report
    events = trace_report.load([path])
    merged_path = os.path.join(args.trace_out, "trace_sample.json")
    with open(merged_path, "w") as f:
        json.dump(trace_report.merge(events), f)
    rows, _ = trace_report.chunk_table(events)
    assert rows, "merged trace has no per-chunk rows"
    print(f"sample trace: {n_events} events, {len(rows)} chunk rows "
          f"-> {merged_path}")
    rec.disable()
    rec.clear()

    # ---- overhead: alternate off/on reps per instrument, keep the
    # best of each (recorder row pair + scanstats row pair)
    wall_off = wall_on = wall_ss = wall_fp = np.inf
    for _ in range(args.reps):
        _, w = run_once(False)
        wall_off = min(wall_off, w)
        _, w = run_once(True)
        wall_on = min(wall_on, w)
        _, w = run_once(False, scanstats=True)
        wall_ss = min(wall_ss, w)
        _, w = run_once(False, fingerprint=True)
        wall_fp = min(wall_fp, w)
    overhead = (wall_on - wall_off) / wall_off * 100.0
    overhead_ss = (wall_ss - wall_off) / wall_off * 100.0
    overhead_fp = (wall_fp - wall_off) / wall_off * 100.0
    proto = (f"best-of-{args.reps}, alternating off/on, "
             f"platform={os.environ.get('JAX_PLATFORMS', '?')}")
    rows = [{
        "scenario": "obs_smoke 4-aircraft FF to simt=20",
        "instrument": "recorder",
        "reps": args.reps,
        "wall_off_s": round(wall_off, 4),
        "wall_on_s": round(wall_on, 4),
        "overhead_pct": round(overhead, 2),
        "trace_events": n_events,
        "chunks": int(n_chunks),
        "parity": "bit-identical",
        "protocol": proto,
    }, {
        "scenario": "obs_smoke 4-aircraft FF to simt=20",
        "instrument": "scanstats",
        "reps": args.reps,
        "wall_off_s": round(wall_off, 4),
        "wall_on_s": round(wall_ss, 4),
        "overhead_pct": round(overhead_ss, 2),
        "chunks": int(n_chunks),
        "parity": "bit-identical",
        "protocol": proto,
    }, {
        "scenario": "obs_smoke 4-aircraft FF to simt=20",
        "instrument": "fingerprint",
        "reps": args.reps,
        "wall_off_s": round(wall_off, 4),
        "wall_on_s": round(wall_fp, 4),
        "overhead_pct": round(overhead_fp, 2),
        "chunks": int(n_chunks),
        "fp": fp["fp"],
        "parity": "bit-identical",
        "protocol": proto,
    }]
    # shared writer: platform tag + BENCH_HISTORY append (the perf
    # sentinel's obs-overhead series)
    import bench
    bench.write_bench_json(args.out, rows)
    print(f"recorder overhead: off {wall_off:.3f}s vs on "
          f"{wall_on:.3f}s = {overhead:+.2f}% -> {args.out}")
    print(f"scanstats overhead: off {wall_off:.3f}s vs on "
          f"{wall_ss:.3f}s = {overhead_ss:+.2f}% -> {args.out}")
    print(f"fingerprint overhead: off {wall_off:.3f}s vs on "
          f"{wall_fp:.3f}s = {overhead_fp:+.2f}% "
          f"(chain {fp['fp']}) -> {args.out}")
    bad = []
    if overhead > 5.0:
        bad.append(f"recorder {overhead:+.2f}%")
    if overhead_ss > 5.0:
        bad.append(f"scanstats {overhead_ss:+.2f}%")
    if overhead_fp > 5.0:
        bad.append(f"fingerprint {overhead_fp:+.2f}%")
    if bad:
        print("OBS SMOKE: overhead above the 5% CI flag line: "
              + ", ".join(bad), file=sys.stderr)
        return 1
    print("obs smoke OK")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except AssertionError as e:
        print(f"OBS SMOKE FAILED: {e}", file=sys.stderr)
        sys.exit(1)
