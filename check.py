#!/usr/bin/env python3
"""Environment capability probe (parity: /root/reference/check.py).

The reference script checks numpy/scipy/Qt/OpenGL/pygame availability
for its GUI stack; this framework's equivalent checks the TPU-native
stack: JAX and its backend devices, the optional acceleration pieces,
the network fabric deps, the compiled host-geodesy extension, and the
data mounts — then runs a one-aircraft smoke simulation.

Run: python check.py        (exit 0 = everything needed is present)
"""
import importlib
import os
import sys

FAIL = 0


def probe(name, what, detail="", optional=False):
    global FAIL
    pad = " " * max(1, 32 - len(what))
    try:
        out = name() if callable(name) else importlib.import_module(name)
        extra = detail(out) if callable(detail) else detail
        print(f"Checking {what}{pad}[OK] {extra}")
        return out
    except Exception as e:  # noqa: BLE001 — a probe must never crash
        if optional:
            # missing optional pieces degrade gracefully: report, but
            # keep exit 0 (the script's contract)
            print(f"Checking {what}{pad}[MISSING] {type(e).__name__}: {e}")
        else:
            print(f"Checking {what}{pad}[FAIL] {type(e).__name__}: {e}")
            FAIL += 1
        return None


print("bluesky_tpu environment check")
print()

probe("numpy", "numpy")
jax = probe("jax", "jax", detail=lambda m: m.__version__)
if jax is not None:
    probe(lambda: jax.devices(), "jax devices",
          detail=lambda d: f"{jax.default_backend()}: "
                           f"{[str(x) for x in d]}")
    probe(lambda: __import__("jax.experimental.pallas", fromlist=["x"]),
          "pallas (TPU kernels)")
probe("flax", "flax (optional)", optional=True)
probe("optax", "optax (optional)", optional=True)
probe("zmq", "pyzmq (network fabric)")
probe("msgpack", "msgpack (wire codec)")

# the compiled host geodesy core (optional; NumPy fallback otherwise)
def _cgeo():
    from bluesky_tpu.ops import hostgeo
    if not hostgeo.compiled:
        raise RuntimeError(
            "not built (optional): cd bluesky_tpu/src_cpp && "
            "python setup.py build_ext --inplace")
    return hostgeo
probe(_cgeo, "cgeo C++ extension (optional)", optional=True)

# data mounts (everything degrades gracefully; see docs/DATA.md)
def _data():
    from bluesky_tpu import settings
    out = []
    for label, p in (("navdata", settings.navdata_path),
                     ("performance", settings.perf_path)):
        out.append(f"{label}: "
                   + (p if p and os.path.isdir(p) else "builtin fallback"))
    return ", ".join(out)
probe(_data, "data paths", detail=lambda s: s, optional=True)

# multi-chip decomposition surface (SHARD REPLICATE/SPATIAL): report
# the visible mesh size; the full 8-device parity matrix is the
# driver/CI dryrun (MULTICHIP_r06.json, __graft_entry__.dryrun_multichip)
def _shard():
    import jax as _jax
    from bluesky_tpu.parallel import sharding as _shd
    nd = len(_jax.devices())
    assert _shd.prepare_spatial and _shd.make_mesh
    return f"{nd} device(s); modes: replicate, spatial"
probe(_shard, "multi-chip shard modes", detail=lambda s: s,
      optional=True)

# one-aircraft smoke sim on whatever backend JAX picked
def _smoke():
    from bluesky_tpu.simulation.sim import Simulation
    sim = Simulation(nmax=8)
    sim.stack.stack("CRE CHK B744 52 4 90 FL200 250; OP; FF 2")
    sim.stack.process()
    sim.run(until_simt=2.0)
    assert sim.traf.ntraf == 1 and float(sim.simt) >= 2.0 - 0.06, \
        f"ntraf={sim.traf.ntraf} simt={float(sim.simt)}"
    return sim
probe(_smoke, "smoke simulation (2 sim-s)",
      detail=lambda s: f"simt={float(s.simt):.2f}s")

print()
if FAIL:
    print(f"{FAIL} probe(s) failed — required pieces are jax, numpy, "
          "pyzmq, msgpack; the rest degrade gracefully.")
print("Result:", "OK" if FAIL == 0 else "INCOMPLETE")
sys.exit(1 if FAIL else 0)
