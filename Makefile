# Developer entry points (parity: /root/reference/Makefile — test/lint/
# build/dist/clean/install; bench and check are this framework's own).
.PHONY: all test test-fast lint build dist clean install uninstall \
	bench check ext chaos mesh-chaos

PYTHON=python3

all: build

# 4 xdist workers when pytest-xdist is installed.  loadscope keeps each
# module on one worker: module-scoped fixtures with stateful command
# chains (test_command_coverage SMOKE) need in-module ordering.
XDIST := $(shell $(PYTHON) -c "import xdist" 2>/dev/null \
	&& echo "-n 4 --dist loadscope")

test:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	$(PYTHON) -m pytest tests/ -q $(XDIST)

test-fast:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	$(PYTHON) -m pytest tests/ -q -m 'not slow' $(XDIST)

# Fault-injection lane: the full chaos suite (tests/test_chaos.py,
# docs/FAULT_TOLERANCE.md recovery matrix), the durability suite
# (atomic snapshots, preemption, BATCH journal crash-resume), the
# overload/straggler suite (admission control, fairness, hedging,
# HEALTH — incl. the slow 16-piece FAULT STRAGGLE acceptance case),
# the packed multi-world serving suite (crash-mid-pack exactly-once
# demux), the self-healing mitigation suite (network/mitigate.py —
# incl. the slow closed-loop FAULT STRAGGLE + LOADSPIKE acceptance
# case), the SDC-defense suite (tests/test_sdc.py — fingerprint fold,
# redundant-execution voting, quarantine, incl. the slow closed-loop
# FAULT BITFLIP acceptance case), the broker-HA suite
# (tests/test_ha.py — lease/fence/reconcile units plus the slow FAULT
# KILLSERVER failover chaos case: SIGKILL the leader mid-BATCH,
# standby takes the lease, workers adopt in-flight pieces, journal-
# verified exactly-once) and the slow fabric cases (kill -9 a real
# worker mid-BATCH, silent-worker reaping).
chaos:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	$(PYTHON) -m pytest tests/test_chaos.py tests/test_durability.py \
	tests/test_overload.py tests/test_fabric_hardening.py \
	tests/test_world_serving.py tests/test_mitigate.py \
	tests/test_sdc.py tests/test_ha.py -q $(XDIST)

# Mesh-epoch recovery lane (docs/FAULT_TOLERANCE.md §mesh epochs):
# MeshGuard unit + MESHKILL e2e + re-shard parity, the journal-replay
# fuzz suite, and the real-process chaos cases — 2-process gloo mesh
# with one host SIGKILLed mid-BATCH, in-fabric FAULT MESHKILL, and the
# heartbeat-only partition no-double-count case.  The SDC-defense
# suite rides this BLOCKING lane too (the chaos lane is advisory):
# fingerprint voting and quarantine are exactly-once-journal
# invariants, same class as the fuzz suite.  The broker-HA fast units
# (tests/test_ha.py -m 'not slow' — lease files, journal fencing,
# reconciliation, discovery arbitration) gate here for the same
# reason; the wall-clock failover chaos case stays in the advisory
# chaos lane.  The gloo test spawns its own 4-device subprocesses, so
# no xdist here.
mesh-chaos:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	$(PYTHON) -m pytest tests/test_meshguard.py tests/test_journal_fuzz.py \
	tests/test_meshchaos.py tests/test_sdc.py -q \
	&& JAX_PLATFORMS=cpu \
	XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	$(PYTHON) -m pytest tests/test_ha.py -q -m 'not slow'

lint:
	@$(PYTHON) -m pyflakes bluesky_tpu tests 2>/dev/null \
	|| $(PYTHON) -m flake8 --select=F bluesky_tpu tests 2>/dev/null \
	|| { $(PYTHON) -m compileall -q bluesky_tpu tests && \
	     echo "pyflakes/flake8 not installed — ran compileall only"; }

check:
	$(PYTHON) check.py

bench:
	$(PYTHON) bench.py

ext:
	cd bluesky_tpu/src_cpp && $(PYTHON) setup.py build_ext --inplace

build: pyproject.toml
	$(PYTHON) -m pip install -e . --no-deps

dist:
	$(PYTHON) -m build

clean:
	rm -rf dist/ build/ *egg-info*
	find . -type d -name '__pycache__' -prune -exec rm -rf {} +

install: build

uninstall:
	$(PYTHON) -m pip uninstall -y bluesky-tpu
