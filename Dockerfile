# Headless bluesky_tpu server (reference parity: /root/reference/Dockerfile,
# docker-compose.yaml — the same "server in a container, clients connect
# over ZMQ" deployment).
#
#   docker build -t bluesky-tpu .
#   docker run -p 11000-11001:11000-11001 bluesky-tpu
#
# For TPU VMs, base on a jax[tpu] image instead and install with
# `pip install -e .[tpu]`.
FROM python:3.12-slim

WORKDIR /app

# Build tools only for the optional cgeo C extension
RUN apt-get update && apt-get install -y --no-install-recommends g++ \
    && rm -rf /var/lib/apt/lists/*

COPY requirements.txt .
RUN pip install --no-cache-dir -r requirements.txt

COPY pyproject.toml README.md ./
COPY bluesky_tpu ./bluesky_tpu
COPY scenario ./scenario
RUN pip install --no-cache-dir -e . \
    && (cd bluesky_tpu/src_cpp && python setup.py build_ext --inplace || \
        echo "cgeo build skipped — NumPy host-geo fallback is automatic")

# Event/stream ports for clients, worker ports stay internal
EXPOSE 11000 11001

# Point at a BlueSky data checkout if you have one (docs/DATA.md):
#   docker run -v /path/to/bluesky/data:/data -e BLUESKY_TPU_DATA=/data ...
CMD ["bluesky-tpu", "--headless"]
